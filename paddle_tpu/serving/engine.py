"""In-process serving engine: dynamic micro-batching over the
AnalysisPredictor.

Reference deployment path: AnalysisPredictor + Paddle Serving (the
reference serves one request per Run call on a private scope;
concurrency = clone-per-thread). TPU-native redesign: the expensive
resource is the COMPILED EXECUTABLE, not a thread — so the engine owns
one batcher thread per model that coalesces concurrent ``infer`` calls
into one device batch under a ``(max_batch_size, max_queue_wait_us)``
policy, pads the batch up to a power-of-two shape bucket (buckets.py:
ragged client sizes hit <= log2(max_batch)+1 executables, all
pre-compiled by a warmup pass at load), dispatches through the
predictor's shared per-shape compile cache, and splits/unpads results
back to each caller bit-exactly.

Admission control: a bounded queue rejects with a structured
``ServerOverloaded`` instead of queueing unboundedly (backpressure the
client can act on), and per-request deadlines expire queued work with
``DeadlineExceeded`` before it wastes a device dispatch. Shutdown
drains gracefully; a batcher thread killed by an unexpected error
fails every queued future with a structured ``BatcherDied`` instead of
hanging its clients. ``engine.stats()`` surfaces the SLO metrics
(p50/p95/p99 latency, queue depth, batch-occupancy histogram, QPS,
compile count), and every dispatch is a profiler ``RecordEvent`` span
(with bucket/rows args) so serving shows up in the chrome trace.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from .. import observability as _obs
from .. import profiler as _profiler
from ..inference import AnalysisConfig, AnalysisPredictor
from .buckets import bucket_for, bucket_sizes, pad_batch
from .metrics import EngineStats

__all__ = ["ServingConfig", "ServingEngine", "ServingError",
           "ServerOverloaded", "DeadlineExceeded", "EngineStopped",
           "BatcherDied", "InvalidRequest"]


# ---------------------------------------------------------------------------
# structured errors
# ---------------------------------------------------------------------------

class ServingError(Exception):
    """Base of every engine-raised error: ``code`` (stable string a
    client can switch on) + ``details`` (JSON-able context)."""

    code = "SERVING_ERROR"

    def __init__(self, message, **details):
        super().__init__(message)
        self.details = details

    def to_dict(self):
        return {"code": self.code, "message": str(self),
                "details": self.details}


class ServerOverloaded(ServingError):
    """Admission rejected: the bounded queue is full. Backpressure —
    retry with backoff or shed load upstream."""
    code = "SERVER_OVERLOADED"


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it reached the device."""
    code = "DEADLINE_EXCEEDED"


class EngineStopped(ServingError):
    """The engine is shut down (or shutting down) for this model."""
    code = "ENGINE_STOPPED"


class BatcherDied(ServingError):
    """The batcher thread died on an unexpected error; queued and
    in-flight requests are failed with this instead of hanging."""
    code = "BATCHER_DIED"


class InvalidRequest(ServingError):
    """Malformed feed (wrong inputs, ragged leading dims, oversize)."""
    code = "INVALID_REQUEST"


# ---------------------------------------------------------------------------
# config + request
# ---------------------------------------------------------------------------

@dataclass
class ServingConfig:
    """Batching/admission policy for one served model.

    - ``max_batch_size``: device batch cap = largest shape bucket.
    - ``max_queue_wait_us``: how long the batcher holds an open batch
      for more requests before dispatching it (the latency the engine
      spends buying occupancy).
    - ``max_queue_size``: admission bound (requests, not rows); a full
      queue rejects with ServerOverloaded.
    - ``default_deadline_ms``: applied to requests that don't carry
      their own; None = no deadline.
    - ``warmup``: pre-compile every bucket at load so no client request
      ever pays a cold XLA compile.
    - ``latency_window``: ring size for percentile/QPS estimation.
    - ``hang_deadline_s``: health-plane stall deadline — a batcher
      that makes no progress for this long WHILE requests are queued
      or in flight gets an unhealthy watchdog verdict (journal
      ``health`` event, ``health_state`` gauge, blackbox dump when a
      dump dir is armed). None disables the watch.
    """

    max_batch_size: int = 64
    max_queue_wait_us: int = 2000
    max_queue_size: int = 256
    default_deadline_ms: Optional[float] = None
    warmup: bool = True
    latency_window: int = 4096
    hang_deadline_s: Optional[float] = 30.0


class _Request:
    __slots__ = ("feed", "rows", "future", "t_enqueue", "deadline")

    def __init__(self, feed, rows, deadline):
        self.feed = feed
        self.rows = rows
        self.future = Future()
        self.t_enqueue = time.monotonic()
        self.deadline = deadline  # monotonic seconds, or None


# ---------------------------------------------------------------------------
# per-model worker
# ---------------------------------------------------------------------------

class _ModelWorker:
    """Queue + batcher thread + stats for one loaded model."""

    def __init__(self, name: str, predictor: AnalysisPredictor,
                 config: ServingConfig):
        self.name = name
        self.predictor = predictor
        self.config = config
        self.buckets = bucket_sizes(config.max_batch_size)
        # admission-time spec per input: (declared dtype | None,
        # trailing-dims template | None, -1 free). Feeds are NORMALIZED
        # to the declared dtype and shape-checked at submit — one
        # client's float64 array or wrong trailing dim must get ITS
        # OWN InvalidRequest, not promote/poison the whole coalesced
        # batch (dtype promotion would also mint fresh compile
        # signatures, unbounding the bucket-compiles guarantee).
        self._input_spec = {}
        for inp in predictor.signature["inputs"]:
            dt = np.dtype(inp["dtype"]) if inp["dtype"] else None
            tail = None
            if inp["shape"] is not None:
                dims = list(inp["shape"])
                if inp["dynamic_dims"] == [0]:
                    tail = dims[1:]
                elif not inp["dynamic_dims"]:
                    tail = dims  # batch-less decl: feed adds dim 0
            self._input_spec[inp["name"]] = (dt, tail)
        self.stats = EngineStats(window=config.latency_window,
                                 model=name)
        # live queue-depth gauge: the router's dispatch signal and a
        # per-model Prometheus series (serving_queue_depth{model=...})
        self._depth_gauge = _obs.registry().gauge(
            "serving_queue_depth", model=name)
        self._queue = []  # FIFO of _Request
        self._cond = threading.Condition()
        self._stopped = False
        self._drain = True
        self._dead_error: Optional[BatcherDied] = None
        self._inflight: List[_Request] = []
        # test seam: called (worker, batch) at the top of every
        # dispatch — chaos tests block it (to hold the queue) or raise
        # through it (to simulate a dying batcher thread)
        self._dispatch_hook = None
        self._compile_base = predictor.exe.compile_count
        self.warmed_buckets: List[int] = []
        if config.warmup:
            self._warmup()
        # health plane: one bump per batcher-loop unit of progress; a
        # silent beacon with work queued/in flight is the wedged
        # batcher the watchdog exists to catch (a DEAD batcher already
        # fails its clients via BatcherDied — this is for the one that
        # neither dies nor dispatches)
        self._beacon = _obs.Beacon("serving_batcher/%s" % name)
        self._health_watch = None
        if config.hang_deadline_s is not None:
            self._health_watch = _obs.get_watchdog().watch(
                "serving_batcher/%s" % name, beacon=self._beacon,
                deadline_s=config.hang_deadline_s,
                pending_fn=lambda: bool(self._queue)
                or bool(self._inflight))
        self._thread = threading.Thread(
            target=self._batcher_loop, daemon=True,
            name="serving-batcher-%s" % name)
        self._thread.start()

    # -- warmup --------------------------------------------------------
    def _warmup_feed(self, batch: int) -> Optional[Dict[str, np.ndarray]]:
        """Zero feed with every dynamic batch dim bound to ``batch``,
        derived from the model signature (sidecar or live program
        declaration). None when any NON-batch dim is dynamic — that
        shape can't be guessed, so its bucket compiles lazily."""
        feed = {}
        for inp in self.predictor.signature["inputs"]:
            if inp["shape"] is None:  # pruned/shape-less feed decl
                return None
            dims = list(inp["shape"])
            dyn = inp["dynamic_dims"]
            if not dims or not dyn:
                # batch-less declaration (append_batch_size=False):
                # the executor's feed convention prepends the batch dim
                dims = [batch] + dims
            elif dyn == [0]:
                dims[0] = batch
            else:
                return None
            feed[inp["name"]] = np.zeros(dims, np.dtype(inp["dtype"]))
        return feed

    def _warmup(self):
        """Pre-compile one executable per bucket, smallest first, so
        no client request ever pays a cold XLA compile. With a warm
        persistent compile cache (PADDLE_TPU_COMPILE_CACHE_DIR shared
        across the fleet) the buckets LOAD instead of compiling —
        replica cold-start and hot-swap warmup become O(read) — and
        the ``serving_warmup`` journal event says which happened: how
        many true XLA compiles this warmup paid vs how many
        executables it reused (in-process or loaded from the cache of
        a sibling process)."""
        from paddle_tpu import compile_cache as _ccache
        exe = self.predictor.exe
        xla0 = exe.xla_compile_count
        loads0 = exe.cache_load_count
        t0 = time.perf_counter()
        for b in self.buckets:
            feed = self._warmup_feed(b)
            if feed is None:
                break
            with _profiler.RecordEvent(
                    "serving_warmup_compile",
                    args={"model": self.name, "bucket": b}):
                self.predictor.predict(feed)
            self.warmed_buckets.append(b)
        # hits from THIS executor's load counter, not the
        # process-global cache counters: a sibling model warming
        # concurrently must not cross-attribute its hits here
        _obs.emit("serving_warmup", model=self.name,
                  buckets=list(self.warmed_buckets),
                  xla_compiles=exe.xla_compile_count - xla0,
                  cache_hits=(exe.cache_load_count - loads0)
                  if _ccache.active() is not None else None,
                  wall_seconds=round(time.perf_counter() - t0, 6))

    # -- client side ---------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None) -> Future:
        if self._dead_error is not None:
            raise self._dead_error
        feed, rows = self._validate(feed)
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _Request(feed, rows, deadline)
        with self._cond:
            if self._stopped:
                raise EngineStopped("model %r is shut down" % self.name,
                                    model=self.name)
            if len(self._queue) >= self.config.max_queue_size:
                self.stats.count("rejected")
                _obs.emit("server_overloaded", model=self.name,
                          queue_depth=len(self._queue))
                raise ServerOverloaded(
                    "queue full for model %r (%d queued)"
                    % (self.name, len(self._queue)),
                    model=self.name, queue_depth=len(self._queue),
                    max_queue_size=self.config.max_queue_size)
            self._queue.append(req)
            self._depth_gauge.set(len(self._queue))
            self._cond.notify()
        return req.future

    def queue_depth(self) -> int:
        """Live admission-queue depth (requests waiting, excluding the
        batch currently on the device)."""
        with self._cond:
            return len(self._queue)

    def _validate(self, feed):
        want = set(self.predictor.feed_names)
        got = set(feed)
        if want != got:
            raise InvalidRequest(
                "model %r expects inputs %s, got %s"
                % (self.name, sorted(want), sorted(got)),
                model=self.name)
        arrs = {}
        for k, v in feed.items():
            arr = np.asarray(v)
            dt, tail = self._input_spec.get(k, (None, None))
            if dt is not None and arr.dtype != dt:
                if not np.can_cast(arr.dtype, dt,
                                   casting="same_kind"):
                    raise InvalidRequest(
                        "input %r has dtype %s, model declares %s"
                        % (k, arr.dtype, dt), model=self.name)
                # normalize to the declared dtype: exactly what the
                # compiled executable computes in; also what keeps a
                # float64 client from promoting its batchmates
                arr = arr.astype(dt)
            if tail is not None:
                got = list(arr.shape[1:])
                want = tail
                if len(got) != len(want) or any(
                        w != -1 and w != g
                        for w, g in zip(want, got)):
                    raise InvalidRequest(
                        "input %r has per-row shape %s, model "
                        "declares %s (-1 free)" % (k, got, want),
                        model=self.name)
            arrs[k] = arr
        rows = {k: (v.shape[0] if v.ndim else 0)
                for k, v in arrs.items()}
        nrows = set(rows.values())
        if len(nrows) != 1 or 0 in nrows:
            raise InvalidRequest(
                "inputs must share one non-empty leading batch dim, "
                "got %s" % rows, model=self.name)
        (n,) = nrows
        if n > self.config.max_batch_size:
            raise InvalidRequest(
                "request batch %d exceeds max_batch_size %d — split "
                "it client-side" % (n, self.config.max_batch_size),
                model=self.name, rows=n,
                max_batch_size=self.config.max_batch_size)
        return arrs, int(n)

    # -- batcher side --------------------------------------------------
    @staticmethod
    def _safe_resolve(fut, value=None, exc=None):
        """Resolve a future the CLIENT may have cancelled concurrently:
        set_result/set_exception on a cancelled (or raced) future
        raises InvalidStateError, and an escaping raise here would kill
        the batcher thread for everyone."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
        except Exception:
            pass

    def _expire(self, req):
        self.stats.count("expired")
        self._safe_resolve(req.future, exc=DeadlineExceeded(
            "request expired after %.1f ms in queue"
            % ((time.monotonic() - req.t_enqueue) * 1e3),
            model=self.name))

    def _pop_live(self):
        """Pop the queue head, expiring dead and skipping
        client-cancelled requests on the way. Caller holds the
        condition lock."""
        while self._queue:
            req = self._queue.pop(0)
            if req.future.cancelled():
                continue
            if req.deadline is not None \
                    and time.monotonic() > req.deadline:
                self._expire(req)
                continue
            return req
        return None

    def _next_batch(self) -> Optional[List[_Request]]:
        """Block until a batch is ready: first request opens the
        batch; it closes when full, when ``max_queue_wait_us`` passes,
        or immediately while draining. Returns None to exit (stopped
        and drained). Plain FIFO: a head that would overflow the
        current batch closes it and opens the next one."""
        cfg = self.config
        with self._cond:
            first = None
            while first is None:
                first = self._pop_live()
                if first is None:
                    if self._stopped:
                        return None
                    self._cond.wait(0.1)
            batch, rows = [first], first.rows
            close_at = time.monotonic() + cfg.max_queue_wait_us / 1e6
            while rows < cfg.max_batch_size:
                if self._queue:
                    nxt = self._queue[0]
                    if nxt.future.cancelled():
                        self._queue.pop(0)
                        continue
                    if nxt.deadline is not None \
                            and time.monotonic() > nxt.deadline:
                        # expire ONLY the head (popping via _pop_live
                        # here would pop-and-drop the next live
                        # request behind it)
                        self._expire(self._queue.pop(0))
                        continue
                    if rows + nxt.rows > cfg.max_batch_size:
                        break
                    self._queue.pop(0)
                    batch.append(nxt)
                    rows += nxt.rows
                    continue
                now = time.monotonic()
                if now >= close_at or self._stopped:
                    break
                self._cond.wait(min(close_at - now, 0.01))
            self._depth_gauge.set(len(self._queue))
            return batch

    def _dispatch(self, batch: List[_Request]):
        rows = sum(r.rows for r in batch)
        bucket = bucket_for(rows, self.buckets)
        joined = {}
        for name in self.predictor.feed_names:
            parts = [r.feed[name] for r in batch]
            joined[name] = parts[0] if len(parts) == 1 \
                else np.concatenate(parts, axis=0)
        joined = pad_batch(joined, rows, bucket)
        try:
            with _profiler.RecordEvent(
                    "serving_dispatch",
                    args={"model": self.name, "bucket": bucket,
                          "rows": rows, "requests": len(batch)}):
                if self._dispatch_hook is not None:
                    # test seam inside the per-batch guard: an
                    # Exception it raises is a batch failure (engine
                    # survives); a BaseException simulates a dying
                    # batcher thread and escapes to _die
                    self._dispatch_hook(self, batch)
                outs = self.predictor.predict(joined)
        except Exception as e:  # per-batch failure; engine survives
            self.stats.count("failed", len(batch))
            for r in batch:
                self._safe_resolve(r.future, exc=e)
            return
        self.stats.record_batch(rows, bucket)
        done = time.monotonic()
        off = 0
        for r in batch:
            # observability: the device shape this request actually
            # executed at (readable after result()) — the engine's
            # bit-exactness contract is "equal to a single-request
            # predict padded to THIS bucket"; see docs/serving.md
            r.future.bucket = bucket
            self._safe_resolve(r.future,
                               [np.asarray(o)[off:off + r.rows]
                                for o in outs])
            off += r.rows
            self.stats.record_request(done - r.t_enqueue, t_done=done)

    def _batcher_loop(self):
        try:
            while True:
                batch = self._next_batch()
                if batch is None:
                    return
                # NOT try/finally: on an escaping BaseException the
                # batch must STAY in _inflight so _die can fail its
                # futures (_dispatch resolves every future on both its
                # success and its per-batch failure paths)
                self._inflight = batch
                self._beacon.bump()  # progress: a batch formed
                self._dispatch(batch)
                self._inflight = []
                self._beacon.bump()  # progress: the batch resolved
        except BaseException as e:  # noqa: B036 — a dying batcher
            # must fail its clients, whatever killed it
            self._die(e)

    def _unwatch(self):
        if self._health_watch is not None:
            _obs.get_watchdog().unwatch(self._health_watch)
            self._health_watch = None

    def _die(self, exc):
        err = BatcherDied(
            "batcher thread for model %r died: %r" % (self.name, exc),
            model=self.name, cause=repr(exc))
        _obs.emit("batcher_died", model=self.name, cause=repr(exc))
        self._unwatch()  # the death is already structured evidence
        self._dead_error = err
        with self._cond:
            self._stopped = True
            pending = self._inflight + self._queue
            self._inflight, self._queue = [], []
            self._depth_gauge.set(0)
            self._cond.notify_all()
        self.stats.count("failed", len(pending))
        for r in pending:
            self._safe_resolve(r.future, exc=err)

    # -- lifecycle -----------------------------------------------------
    def shutdown(self, drain=True, timeout: Optional[float] = None):
        self._unwatch()
        with self._cond:
            self._stopped = True
            pending = [] if drain else list(self._queue)
            if not drain:
                self._queue = []
                self._depth_gauge.set(0)
            self._cond.notify_all()
        for r in pending:
            self._safe_resolve(r.future, exc=EngineStopped(
                "model %r shut down without draining" % self.name,
                model=self.name))
        self._thread.join(timeout)

    def snapshot(self) -> dict:
        s = self.stats.snapshot()
        with self._cond:
            s["queue_depth"] = len(self._queue)
        s["model"] = self.name
        s["buckets"] = list(self.buckets)
        s["warmed_buckets"] = list(self.warmed_buckets)
        s["compiles"] = (self.predictor.exe.compile_count
                         - self._compile_base)
        return s


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class ServingEngine:
    """Hosts one or more loaded inference models, each behind its own
    micro-batching worker. ``infer`` returns a Future; ``infer_sync``
    blocks. Usable as a context manager (drains on exit)."""

    def __init__(self, model=None, config: Optional[ServingConfig] = None,
                 name: str = "default", metrics_port=None):
        """``metrics_port``: when not None, start the process-wide
        Prometheus ``/metrics`` export thread on that port (0 = any
        free port; see ``engine.metrics_server.port``). Stopped at
        shutdown."""
        self._workers: Dict[str, _ModelWorker] = {}
        self._default: Optional[str] = None
        self._config = config
        self.metrics_server = None
        if metrics_port is not None:
            self.metrics_server = _obs.start_metrics_server(
                port=metrics_port)
        if model is not None:
            self.add_model(name, model, config)

    def add_model(self, name: str, model,
                  config: Optional[ServingConfig] = None):
        """``model``: an AnalysisPredictor, or a save_inference_model
        directory (loaded through AnalysisConfig with default passes).
        Returns self for chaining."""
        if name in self._workers:
            raise InvalidRequest("model %r already added" % name,
                                 model=name)
        if not isinstance(model, AnalysisPredictor):
            model = AnalysisPredictor(AnalysisConfig(str(model)))
        self._workers[name] = _ModelWorker(
            name, model, config or self._config or ServingConfig())
        if self._default is None:
            self._default = name
        return self

    def remove_model(self, name: str, drain: bool = True,
                     timeout: Optional[float] = None):
        """Unload one model: stop its worker (``drain=True`` serves
        everything already queued first) and drop it from the engine.
        The versioned hot-swap path uses this to retire a drained old
        version while its successor keeps serving."""
        if name not in self._workers:
            raise InvalidRequest("no model %r loaded (have %s)"
                                 % (name, sorted(self._workers)),
                                 model=name)
        worker = self._workers.pop(name)
        worker.shutdown(drain=drain, timeout=timeout)
        if self._default == name:
            self._default = min(self._workers) if self._workers \
                else None
        return self

    def _worker(self, model: Optional[str]) -> _ModelWorker:
        name = model or self._default
        if name is None or name not in self._workers:
            raise InvalidRequest("no model %r loaded (have %s)"
                                 % (name, sorted(self._workers)),
                                 model=name)
        return self._workers[name]

    # -- serving -------------------------------------------------------
    def infer(self, feed: Dict[str, np.ndarray],
              model: Optional[str] = None,
              deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request (all inputs share a leading batch dim);
        resolves to the per-output list of np arrays for exactly this
        request's rows. Raises ServerOverloaded/EngineStopped/
        InvalidRequest synchronously; DeadlineExceeded/BatcherDied
        surface through the Future."""
        return self._worker(model).submit(feed, deadline_ms=deadline_ms)

    def infer_sync(self, feed, model=None, deadline_ms=None,
                   timeout: Optional[float] = None):
        return self.infer(feed, model=model,
                          deadline_ms=deadline_ms).result(timeout)

    # -- introspection -------------------------------------------------
    def stats(self, model: Optional[str] = None) -> dict:
        """SLO snapshot. Single-model engines return that model's dict
        directly; multi-model engines return {"models": {name: dict}}
        unless ``model`` picks one."""
        if model is not None or len(self._workers) == 1:
            return self._worker(model).snapshot()
        return {"models": {n: w.snapshot()
                           for n, w in self._workers.items()}}

    def models(self):
        return sorted(self._workers)

    def queue_depth(self, model: Optional[str] = None) -> int:
        """Live queued-request count: one model's depth, or (model
        None with several loaded) the whole engine's — the load signal
        replicas piggyback to the serving router, also exported as the
        ``serving_queue_depth{model=...}`` gauge."""
        if model is not None or len(self._workers) == 1:
            return self._worker(model).queue_depth()
        return sum(w.queue_depth() for w in self._workers.values())

    # -- lifecycle -----------------------------------------------------
    def shutdown(self, drain=True, timeout: Optional[float] = None):
        """Stop accepting work. ``drain=True`` serves everything
        already queued first; ``drain=False`` fails queued futures
        with EngineStopped."""
        for w in self._workers.values():
            w.shutdown(drain=drain, timeout=timeout)
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)
        return False
