"""Model-signature compatibility for versioned hot-swap.

A fleet flips admissions from model v1 to v2 while v1 CLIENTS keep
sending the same feeds — so v2 must accept every request v1 accepted
and answer in the shape v1 clients parse. ``signature_compat`` checks
exactly that over the ``__signature__.json`` sidecar dicts
(``io.infer_signature`` schema: per-tensor name, dtype, dims with -1
dynamic):

  - input NAME SETS must match exactly — a new required input breaks
    every live client (they don't send it), a dropped one makes their
    feeds InvalidRequest;
  - input dtypes must match exactly — the engine normalizes feeds to
    the DECLARED dtype, so a change silently alters what the compiled
    program computes on old clients' data;
  - input dims: same rank; a static dim must stay the same size, and a
    dynamic (-1) dim must stay dynamic. v2 MAY relax a static dim to
    dynamic (old clients' fixed size still validates);
  - outputs are positional to clients: same count, same dtypes, same
    rank, static output dims unchanged (relaxing to dynamic allowed).

``signature_compat`` returns the list of human-readable
incompatibilities (empty = safe to swap); ``SignatureMismatch`` is the
structured error the router raises from it, carrying the same list so
an operator can see every reason at once instead of fixing them one
rejected swap at a time.
"""

from __future__ import annotations

from typing import List

from .engine import ServingError

__all__ = ["signature_compat", "SignatureMismatch"]


class SignatureMismatch(ServingError):
    """The proposed model version would break live clients of the
    currently-served version; the swap is refused. ``details``
    carries the full problem list."""
    code = "SIGNATURE_MISMATCH"


def _by_name(entries):
    return {e["name"]: e for e in entries or []}


def _dims_compat(old_e, new_e, what, problems):
    os_, ns = old_e.get("shape"), new_e.get("shape")
    if os_ is None and ns is None:
        return
    if os_ is None or ns is None:
        problems.append(
            "%s %r: declared shape %s -> %s (shape-less and shaped "
            "declarations are not interchangeable)"
            % (what, old_e["name"], os_, ns))
        return
    if len(os_) != len(ns):
        problems.append(
            "%s %r: rank %d -> %d (clients' arrays would no longer "
            "validate)" % (what, old_e["name"], len(os_), len(ns)))
        return
    for i, (od, nd) in enumerate(zip(os_, ns)):
        if od == nd:
            continue
        if od != -1 and nd == -1:
            continue  # static -> dynamic: old fixed size still valid
        if od == -1:
            problems.append(
                "%s %r dim %d: dynamic (-1) -> static %d (clients "
                "bound other sizes to this dim)"
                % (what, old_e["name"], i, nd))
        else:
            problems.append(
                "%s %r dim %d: static %d -> %d (clients send %d)"
                % (what, old_e["name"], i, od, nd, od))


def signature_compat(old: dict, new: dict) -> List[str]:
    """Can ``new`` serve every live client of ``old``? Returns the
    list of incompatibilities (empty list = compatible). ``old`` /
    ``new`` are ``__signature__.json`` dicts (io.infer_signature)."""
    problems: List[str] = []
    old_in, new_in = _by_name(old.get("inputs")), \
        _by_name(new.get("inputs"))
    for name in sorted(set(old_in) - set(new_in)):
        problems.append(
            "input %r removed (v1 clients still send it, which the "
            "engine rejects as unexpected)" % name)
    for name in sorted(set(new_in) - set(old_in)):
        problems.append(
            "input %r added (v1 clients don't send it, so every "
            "request would be rejected as incomplete)" % name)
    for name in sorted(set(old_in) & set(new_in)):
        oe, ne = old_in[name], new_in[name]
        if oe.get("dtype") != ne.get("dtype"):
            problems.append(
                "input %r: dtype %s -> %s (feeds are normalized to "
                "the declared dtype; old clients' data would be "
                "reinterpreted)" % (name, oe.get("dtype"),
                                    ne.get("dtype")))
        _dims_compat(oe, ne, "input", problems)
    old_out = old.get("outputs") or []
    new_out = new.get("outputs") or []
    if len(old_out) != len(new_out):
        problems.append(
            "output count %d -> %d (clients unpack outputs "
            "positionally)" % (len(old_out), len(new_out)))
    else:
        for i, (oe, ne) in enumerate(zip(old_out, new_out)):
            if oe.get("dtype") != ne.get("dtype"):
                problems.append(
                    "output %d (%r): dtype %s -> %s"
                    % (i, oe["name"], oe.get("dtype"), ne.get("dtype")))
            _dims_compat(oe, ne, "output", problems)
    return problems
