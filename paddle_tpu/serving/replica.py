"""One serving-fleet replica: a ``ServingEngine`` behind the PR 5 RPC
transport.

The in-process engine (engine.py) is the unit of compute; this module
makes it a FLEET citizen: an ``RPCServer`` (distributed/rpc.py — the
same native transport, deadlines, and wire framing the PS runtime
proved fault-tolerant) serving three verbs:

  - **INFER** — one inference request. The wire name is
    ``model@@tid@@seq@@trace`` (``pack_wire_name``), so the router's
    trainer-id/sequence/trace metadata rides exactly like a trainer's
    SEND and the replica's ``rpc_server:INFER`` span links into ONE
    merged fleet trace (tools/trace_merge.py). The payload is a JSON
    header + ``io.serialize_tensor`` frames (``pack_blob``). Handled
    DEFERRED: the engine's future resolves on a batcher thread and the
    responder is called from there, so a slow batch never blocks the
    drain thread. Every response — success or structured error —
    piggybacks the replica's live load (batcher queue depth + EWMA
    latency) so the router's least-loaded dispatch stays fresh without
    dedicated polling RPCs.
  - **HEARTBEAT** — the router's liveness probe; answers with the same
    load snapshot and journals ``heartbeat_recv`` (the clock-offset
    raw material trace_merge pairs with the router's
    ``heartbeat_rtt``).
  - **CTRL** — the admin channel for versioned hot-swap: ``stats`` /
    ``signature`` / ``load_version`` (load + warm v2 NEXT TO the live
    version) / ``flip`` (atomically switch new admissions) /
    ``drain_unload`` (retire the drained old version). Slow ops run on
    a background thread and answer through the deferred responder so
    warmup compiles never stall heartbeats.

Versioning: each loaded version is its own engine worker named
``<model>@<version>``; ``_active`` maps model -> admitted version and
is flipped under a lock, so the swap is atomic at admission
granularity — in-flight v1 requests finish on v1, new ones land on v2.

Run standalone (the launcher's ``--serving_replicas`` children and
``tools/load_gen.py --replicas`` use this):

    python -m paddle_tpu.serving.replica --model-dir DIR --port 0
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Dict, List, Optional

import numpy as np

from .. import observability as _obs
from ..distributed.rpc import RPCServer, unpack_wire_meta
from ..io import deserialize_tensor, serialize_tensor
from .engine import (InvalidRequest, ServingConfig, ServingEngine,
                     ServingError)

__all__ = ["ServingReplica", "pack_blob", "unpack_blob", "serve_main"]


# ---------------------------------------------------------------------------
# wire payloads: JSON header + tensor frames
# ---------------------------------------------------------------------------

def pack_blob(meta: dict, arrays=()) -> bytes:
    """``u32 header_len | json header | serialize_tensor frames``.
    The header's ``n_arrays`` is stamped here so unpack never guesses."""
    arrays = [np.asarray(a) for a in arrays]
    meta = dict(meta, n_arrays=len(arrays))
    head = json.dumps(meta, sort_keys=True, default=repr).encode()
    parts = [struct.pack("<I", len(head)), head]
    parts.extend(serialize_tensor(a) for a in arrays)
    return b"".join(parts)


def unpack_blob(payload: bytes):
    """Inverse of ``pack_blob`` -> (meta, [ndarray, ...])."""
    (hlen,) = struct.unpack_from("<I", payload, 0)
    meta = json.loads(payload[4:4 + hlen].decode())
    arrays = []
    off = 4 + hlen
    for _ in range(int(meta.get("n_arrays", 0))):
        arr, off = deserialize_tensor(payload, off)
        arrays.append(arr)
    return meta, arrays


# ---------------------------------------------------------------------------
# replica
# ---------------------------------------------------------------------------

class ServingReplica:
    """Hosts one ``ServingEngine`` behind an RPC endpoint, with
    versioned models and piggybacked load reporting."""

    def __init__(self, model=None, config: Optional[ServingConfig] = None,
                 name: str = "default", version: str = "v1",
                 endpoint: str = "127.0.0.1:0", replica_id: int = 0,
                 metrics_port=None, mesh_axes: Optional[dict] = None,
                 group_rank: int = 0, group_size: int = 1):
        """``mesh_axes`` (e.g. ``{"tp": 2}`` / ``{"sp": 2}``): serve
        the model as one pjit'd forward over a device mesh
        (AnalysisPredictor.enable_mesh) — the sharded replica-GROUP
        executor. ``group_rank``/``group_size``: this process's place
        in its group; rank 0 is the group's executor member (receives
        INFER), ranks > 0 are shard members — they hold the group's
        lease surface (HEARTBEAT/CTRL stats) and, on a TPU pod, the
        other hosts of the shared mesh (jax.distributed; the CPU
        probe's rank 0 emulates the whole group mesh with virtual
        devices). An INFER landing on a shard member answers a
        structured error, never silence."""
        self.replica_id = int(replica_id)
        self.group_rank = int(group_rank)
        self.group_size = int(group_size)
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        self.engine = ServingEngine(config=config,
                                    metrics_port=metrics_port)
        self._config = config
        self._mu = threading.Lock()
        self._active: Dict[str, str] = {}      # model -> admitted ver
        self._versions: Dict[str, List[str]] = {}
        self._default_model: Optional[str] = None
        self._crashed = False
        if model is not None and self.group_rank == 0:
            self._register(name, version, model, config)
        self.server = RPCServer(endpoint)
        self.endpoint = self.server.endpoint
        self.server.register_deferred("INFER", self._on_infer)
        self.server.register_deferred("CTRL", self._on_ctrl)
        self.server.register("HEARTBEAT", self._on_heartbeat)

    def _make_model(self, source):
        """Predictor for ``source`` (dir or predictor), mesh-sharded
        when this replica serves a group mesh."""
        from ..inference import AnalysisConfig, AnalysisPredictor
        if not isinstance(source, AnalysisPredictor):
            source = AnalysisPredictor(AnalysisConfig(str(source)))
        if self.mesh_axes:
            source.enable_mesh(self.mesh_axes)
        return source

    # -- versioned model registry --------------------------------------
    @staticmethod
    def _worker_name(model: str, version: str) -> str:
        return "%s@%s" % (model, version)

    def _register(self, model, version, source, config):
        self.engine.add_model(self._worker_name(model, version),
                              self._make_model(source), config)
        with self._mu:
            vs = self._versions.setdefault(model, [])
            if version not in vs:
                vs.append(version)
            self._active.setdefault(model, version)
            if self._default_model is None:
                self._default_model = model

    def _resolve(self, model: Optional[str]):
        """-> (model, active_version, worker_name) for admission."""
        with self._mu:
            m = model or self._default_model
            v = self._active.get(m)
        if v is None:
            raise InvalidRequest(
                "replica %d serves no model %r (have %s)"
                % (self.replica_id, m, sorted(self._versions)),
                model=m, replica=self.replica_id)
        return m, v, self._worker_name(m, v)

    # -- load piggyback ------------------------------------------------
    def load_snapshot(self) -> dict:
        """The scalars the router ranks replicas by, shipped on every
        INFER response and heartbeat."""
        depth = 0
        ewma = None
        for w in list(self.engine._workers.values()):
            depth += w.queue_depth()
            e = w.stats.ewma_ms
            if e is not None:
                ewma = e if ewma is None else max(ewma, e)
        return {"replica_id": self.replica_id, "queue_depth": depth,
                "ewma_ms": ewma}

    def _err_meta(self, exc) -> dict:
        err = exc.to_dict() if isinstance(exc, ServingError) else {
            "code": "SERVING_ERROR", "message": repr(exc),
            "details": {}}
        return {"ok": False, "error": err, "load": self.load_snapshot()}

    # -- handlers ------------------------------------------------------
    def _respond(self, responder, status, payload):
        """A crashed replica answers nothing (chaos contract: die like
        a SIGKILLed process); a closed peer socket is also survivable
        — the router's deadline/retry owns that failure."""
        if self._crashed:
            return
        try:
            responder(status, payload)
        except Exception:
            pass

    def _on_infer(self, wire, payload, responder):
        base, _tid, _seq, _tok = unpack_wire_meta(wire)
        try:
            if self.group_rank != 0:
                raise InvalidRequest(
                    "replica %d is shard member rank %d of a "
                    "group-of-%d — INFER dispatches to the group's "
                    "rank-0 executor" % (self.replica_id,
                                         self.group_rank,
                                         self.group_size),
                    replica=self.replica_id, group_rank=self.group_rank)
            meta, arrays = unpack_blob(payload)
            feed = dict(zip(meta["inputs"], arrays))
            m, v, wname = self._resolve(base or None)
            fut = self.engine.infer(feed, model=wname,
                                    deadline_ms=meta.get("deadline_ms"))
        except Exception as e:
            self._respond(responder, 0, pack_blob(self._err_meta(e)))
            return

        def done(f, _v=v):
            try:
                outs = f.result()
            except Exception as e:
                self._respond(responder, 0,
                              pack_blob(self._err_meta(e)))
                return
            meta_out = {"ok": True, "version": _v,
                        "load": self.load_snapshot()}
            self._respond(responder, 0, pack_blob(meta_out, outs))

        fut.add_done_callback(done)

    def _on_heartbeat(self, wire, payload):
        _base, tid, seq, _tok = unpack_wire_meta(wire)
        if seq is not None:
            _obs.emit("heartbeat_recv", tid=tid, beat=seq,
                      endpoint=self.endpoint)
        return pack_blob({"ok": True, "load": self.load_snapshot()})

    def _on_ctrl(self, wire, payload, responder):
        try:
            meta, _ = unpack_blob(payload)
        except Exception as e:
            self._respond(responder, 0, pack_blob(self._err_meta(e)))
            return
        op = meta.get("op")
        if op in ("load_version", "drain_unload"):
            # slow ops (warmup compiles, queue drain) must not stall
            # the drain thread: run aside, answer via the responder
            threading.Thread(
                target=self._ctrl_slow, args=(op, meta, responder),
                daemon=True,
                name="serving-ctrl-%s" % op).start()
            return
        try:
            out = self._ctrl_fast(op, meta)
        except Exception as e:
            out = self._err_meta(e)
        self._respond(responder, 0, pack_blob(out))

    def _ctrl_fast(self, op, meta):
        if op == "stats":
            return {"ok": True, "stats": self.stats()}
        if op == "signature":
            _m, v, wname = self._resolve(meta.get("model"))
            sig = self.engine._workers[wname].predictor.signature
            return {"ok": True, "version": v, "signature": sig}
        if op == "flip":
            return self._flip(meta["model"], meta["version"])
        raise InvalidRequest("unknown CTRL op %r" % op, op=op)

    def _ctrl_slow(self, op, meta, responder):
        try:
            if op == "load_version":
                out = self._load_version(meta)
            else:
                out = self._drain_unload(meta)
        except Exception as e:
            out = self._err_meta(e)
        self._respond(responder, 0, pack_blob(out))

    def _load_version(self, meta):
        m, v = meta["model"], meta["version"]
        wname = self._worker_name(m, v)
        self._register(m, v, meta["model_dir"], self._config)
        worker = self.engine._workers[wname]
        _obs.emit("model_version_loaded", model=m, version=v,
                  replica=self.replica_id,
                  warmed_buckets=list(worker.warmed_buckets))
        return {"ok": True, "model": m, "version": v,
                "warmed_buckets": list(worker.warmed_buckets),
                "buckets": list(worker.buckets),
                "signature": worker.predictor.signature}

    def _flip(self, m, v):
        wname = self._worker_name(m, v)
        with self._mu:
            if wname not in self.engine._workers:
                raise InvalidRequest(
                    "cannot flip %r to unloaded version %r (loaded: "
                    "%s) — CTRL load_version first"
                    % (m, v, self._versions.get(m, [])), model=m,
                    version=v)
            previous = self._active.get(m)
            self._active[m] = v
        _obs.emit("model_flip", model=m, version=v, previous=previous,
                  replica=self.replica_id)
        return {"ok": True, "model": m, "version": v,
                "previous": previous}

    def _drain_unload(self, meta):
        m, v = meta["model"], meta["version"]
        with self._mu:
            if self._active.get(m) == v:
                raise InvalidRequest(
                    "version %r is still ADMITTING for model %r — "
                    "flip to the successor before drain_unload"
                    % (v, m), model=m, version=v)
        self.engine.remove_model(self._worker_name(m, v), drain=True,
                                 timeout=meta.get("timeout_s", 60))
        with self._mu:
            vs = self._versions.get(m, [])
            if v in vs:
                vs.remove(v)
        _obs.emit("model_version_unloaded", model=m, version=v,
                  replica=self.replica_id)
        return {"ok": True, "model": m, "version": v}

    # -- introspection / lifecycle ------------------------------------
    def stats(self) -> dict:
        with self._mu:
            models = {m: {"active": self._active.get(m),
                          "versions": list(vs)}
                      for m, vs in self._versions.items()}
        return {"replica_id": self.replica_id,
                "endpoint": self.endpoint,
                "models": models,
                "group_rank": self.group_rank,
                "group_size": self.group_size,
                "mesh_axes": self.mesh_axes,
                "load": self.load_snapshot(),
                "engine": self.engine.stats()
                if self.engine._workers else {}}

    def start(self):
        self.server.start()
        return self

    def crash(self):
        """Chaos seam: die like a SIGKILLed replica process — sockets
        closed NOW, in-flight INFERs never answered. The router's
        deadlines + lease monitor must absorb it."""
        self._crashed = True
        self.server._crash()

    def shutdown(self, drain=True):
        self.server.shutdown()
        self.engine.shutdown(drain=drain, timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


# ---------------------------------------------------------------------------
# standalone entry point (launcher children / load_gen --replicas)
# ---------------------------------------------------------------------------

def serve_main(argv=None):
    """Run one replica process: load the model, announce the bound
    endpoint as ``REPLICA_READY <endpoint>`` on stdout, serve until
    stdin closes (the parent's handle on our lifetime) or SIGTERM."""
    import argparse
    import os
    import signal
    import sys

    ap = argparse.ArgumentParser(description=serve_main.__doc__)
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--name", default="default")
    ap.add_argument("--version", default="v1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replica-id", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--wait-us", type=int, default=2000)
    ap.add_argument("--queue-size", type=int, default=256)
    ap.add_argument("--metrics-port", type=int, default=None)
    ap.add_argument("--mesh-axes", default=None,
                    help="JSON axis dict (e.g. '{\"tp\": 2}') — serve "
                    "the model as one pjit'd forward over this mesh "
                    "(sharded replica group executor). Sizes multiply "
                    "to the local device count.")
    ap.add_argument("--group-rank", type=int, default=0,
                    help="this process's rank in its replica group "
                    "(0 = executor member, >0 = shard member)")
    ap.add_argument("--group-size", type=int, default=1)
    ap.add_argument("--dispatch-floor-ms", type=float, default=0.0,
                    help="CPU-probe device-time emulation: minimum "
                    "wall time per device dispatch (installed via the "
                    "engine's dispatch hook). A fleet's scaling story "
                    "is about replicas' DEVICE time running in "
                    "parallel; on a shared-core CPU host the real "
                    "compute of N replicas serializes on the cores, "
                    "so the scaling bench pins dispatch time to a "
                    "constant instead — 0 (default) disables.")
    args = ap.parse_args(argv)

    if not os.environ.get("PADDLE_TPU_ROLE"):
        _obs.set_role("serving-%d" % args.replica_id)
    cfg = ServingConfig(max_batch_size=args.max_batch,
                        max_queue_wait_us=args.wait_us,
                        max_queue_size=args.queue_size)
    mesh_axes = json.loads(args.mesh_axes) if args.mesh_axes else None
    if mesh_axes:
        import numpy as _np
        want = int(_np.prod(list(mesh_axes.values())))
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # CPU probe: back the group mesh with virtual host devices
            # (a TPU host sees its real chips instead)
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=%d"
                % max(want, 1)).strip()
    replica = ServingReplica(
        args.model_dir, cfg, name=args.name, version=args.version,
        endpoint="127.0.0.1:%d" % args.port,
        replica_id=args.replica_id,
        metrics_port=args.metrics_port,
        mesh_axes=mesh_axes, group_rank=args.group_rank,
        group_size=args.group_size)
    if args.dispatch_floor_ms > 0:
        import time as _time
        floor_s = args.dispatch_floor_ms / 1e3

        def _floor(worker, batch, _s=floor_s):
            _time.sleep(_s)

        for w in replica.engine._workers.values():
            w._dispatch_hook = _floor
    replica.start()
    print("REPLICA_READY %s" % replica.endpoint, flush=True)
    _obs.emit("replica_started", endpoint=replica.endpoint,
              replica=args.replica_id)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    # health plane: watchdog (the engine's batcher watches are already
    # armed) + flight recorder. The SIGTERM chain dumps the black box
    # FIRST, then falls through to the graceful stop handler above.
    _obs.arm_process(signals=True)
    # parent closes our stdin to stop us (portable even when signals
    # are swallowed by a shell wrapper)
    def stdin_watch():
        try:
            while sys.stdin.read(1):
                pass
        except Exception:
            pass
        stop.set()

    threading.Thread(target=stdin_watch, daemon=True).start()
    while not stop.wait(0.1):
        pass
    replica.shutdown()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(serve_main())
