"""Serving engine: dynamic micro-batching, shape buckets, backpressure,
and latency SLO metrics over the AnalysisPredictor.

The deploy-side subsystem matching PR1/PR2's train-side ones: the
reference's serving story (AnalysisPredictor + Paddle Serving) amortizes
one process per model; the TPU-native redesign amortizes one COMPILED
EXECUTABLE PER SHAPE BUCKET across every concurrent client — see
``engine.py`` (batching/admission/lifecycle), ``buckets.py`` (pow-2
bucket math), ``metrics.py`` (SLO accumulators), ``docs/serving.md``.

Fleet layer: ``replica.py`` puts one engine behind the RPC transport
(INFER/HEARTBEAT/CTRL verbs, piggybacked load, versioned models) and
``router.py`` fronts N replicas with queue-depth-aware dispatch,
structured shedding, lease-based eviction with transparent retry, and
``signature_compat``-gated hot-swap — docs/serving.md §"Fleet serving".

Sparse plane: ``sparse.py`` serves the >HBM recommender straight from
the LIVE pserver tables trainers are pushing into — device row tier
over host Tier 0 over the spill+snapshot authority, with a
bounded-staleness coherence gate — docs/serving.md §"Sparse serving".
"""

from .buckets import bucket_for, bucket_sizes, pad_batch  # noqa: F401
from .engine import (BatcherDied, DeadlineExceeded,  # noqa: F401
                     EngineStopped, InvalidRequest, ServerOverloaded,
                     ServingConfig, ServingEngine, ServingError)
from .metrics import EngineStats  # noqa: F401
from .replica import ServingReplica  # noqa: F401
from .router import (ReplicaUnavailable, RouterConfig,  # noqa: F401
                     ServingRouter)
from .signature import SignatureMismatch, signature_compat  # noqa: F401
from .sparse import (SparseServingConfig,  # noqa: F401
                     SparseServingReplica, StaleRows)

__all__ = ["ServingEngine", "ServingConfig", "ServingError",
           "ServerOverloaded", "DeadlineExceeded", "EngineStopped",
           "BatcherDied", "InvalidRequest", "EngineStats",
           "bucket_sizes", "bucket_for", "pad_batch",
           "ServingReplica", "ServingRouter", "RouterConfig",
           "ReplicaUnavailable", "signature_compat",
           "SignatureMismatch", "SparseServingReplica",
           "SparseServingConfig", "StaleRows"]
