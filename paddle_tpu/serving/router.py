"""Serving-fleet router: one front door over N ``ServingReplica``s.

One process never carries production traffic (the reference's fleet
heritage); the router is how N single-engine replicas become one
service:

  - **queue-depth-aware least-loaded dispatch** — every INFER response
    and heartbeat piggybacks the replica's live batcher queue depth +
    EWMA latency; dispatch scores each healthy replica as
    ``reported_queue_depth + local_inflight`` (the local in-flight
    count keeps the score honest between piggybacks) and picks the
    minimum, tie-breaking on EWMA latency. ``policy="round_robin"``
    keeps the naive baseline selectable — the bench's p99-under-skew
    comparison is a one-flag A/B.
  - **structured shedding** — when every healthy replica is saturated
    (reported depth at/over ``shed_queue_depth``) or the router's own
    pending cap is hit, ``infer`` raises ``ServerOverloaded``
    SYNCHRONOUSLY, exactly like the in-process engine: backpressure
    the client can act on, not a deep queue that melts p99 for
    everyone.
  - **replica health = PR 5 lease posture, inverted** — a per-replica
    heartbeat thread probes each replica on a dedicated connection;
    a replica silent past ``lease_timeout_s`` is EVICTED (journalled
    ``replica_evicted``, dispatch stops choosing it) and re-admitted
    when it answers again. In-flight requests to a dying replica fail
    by RPC deadline — never a hang — and are transparently RETRIED on
    a healthy replica (inference is read-only, so replay is always
    safe; contrast the seq-dedup machinery writes need).
  - **versioned hot-swap** — ``swap_model(model_dir)`` refuses a
    successor whose ``__signature__.json`` would break live clients
    (``signature_compat``), then loads + WARMS v2 next to v1 on every
    replica, atomically flips admissions, and drains/unloads v1 —
    zero failed requests through the flip.

The client surface mirrors ``ServingEngine`` (``infer`` -> Future,
``infer_sync``, ``stats``, ``shutdown``), so ``tools/load_gen.py``
drives an engine and a fleet with the same loop.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from .. import observability as _obs
from ..chaos import faultpoints as _faults
from ..distributed.rpc import (DeadlineExceededError, RPCClient,
                               RpcError)
from ..io import SIGNATURE_FILENAME
from .engine import (BatcherDied, DeadlineExceeded, EngineStopped,
                     InvalidRequest, ServerOverloaded, ServingError)
from .replica import pack_blob, unpack_blob
from .signature import SignatureMismatch, signature_compat

__all__ = ["RouterConfig", "ServingRouter", "ReplicaUnavailable"]


class ReplicaUnavailable(ServingError):
    """Every dispatch attempt for this request failed at the transport
    (replicas dead/unreachable within the retry budget). Structured —
    a future resolves with this, it never hangs."""
    code = "REPLICA_UNAVAILABLE"


_ERROR_TYPES = {c.code: c for c in
                (ServerOverloaded, DeadlineExceeded, EngineStopped,
                 BatcherDied, InvalidRequest, ReplicaUnavailable,
                 SignatureMismatch, ServingError)}


def _error_from_meta(meta: dict) -> ServingError:
    err = meta.get("error") or {}
    cls = _ERROR_TYPES.get(err.get("code"), ServingError)
    return cls(err.get("message", "replica error"),
               **(err.get("details") or {}))


@dataclass
class RouterConfig:
    """Dispatch/admission policy for one router.

    - ``policy``: ``least_loaded`` (queue-depth-aware, the default) or
      ``round_robin`` (the baseline the bench compares against).
    - ``shed_queue_depth``: a replica reporting this queue depth (or
      more) counts saturated; when EVERY healthy replica is saturated
      the router sheds with ``ServerOverloaded``.
    - ``max_pending``: router-level admission cap on futures in
      flight.
    - ``max_retries``: transport-failure retries per request (each on
      a different replica while any untried healthy one remains).
    - ``lease_timeout_s`` / ``heartbeat_interval_s``: replica
      liveness lease (PR 5 semantics, router-side).
    - ``rpc_deadline_s``: per-INFER transport deadline for requests
      that carry no deadline of their own — the bound that turns a
      dead replica into a retryable error instead of a hang.
    - ``max_concurrency``: dispatch worker threads (each blocked
      request occupies one).
    - ``hang_deadline_s``: health-plane stall deadline — a router
      with pending requests and NO completions for this long gets an
      unhealthy watchdog verdict (every per-attempt failure path has
      its own deadline, so this firing means the dispatch machinery
      itself is wedged). None disables the watch.
    """

    policy: str = "least_loaded"
    # replica GROUPS (sharded group inference, docs/parallel.md): the
    # endpoint list is consecutive groups of this size; member 0 of
    # each group is its executor (one pjit'd forward over the group's
    # mesh), the rest are shard members. Dispatch targets healthy
    # groups' executors; ANY member's lease lapsing evicts the WHOLE
    # group (a mesh missing one host cannot answer), and in-flight
    # requests retry on another group — a future never hangs.
    group_size: int = 1
    shed_queue_depth: int = 256
    max_pending: int = 4096
    max_retries: int = 3
    lease_timeout_s: float = 2.0
    heartbeat_interval_s: float = 0.25
    rpc_deadline_s: float = 30.0
    connect_timeout_s: float = 5.0
    # dispatch-path connects FAIL FAST: RPCClient's connect loop
    # retries refused connections for its whole budget ("server may be
    # starting" — right for a pserver restart, wrong mid-dispatch
    # where a dead replica must cost ~one RTT before the request is
    # retried on a live one). The health loop keeps using
    # connect_timeout_s — it is the path that waits for restarts.
    dispatch_connect_timeout_s: float = 1.0
    max_concurrency: int = 32
    router_id: int = 0
    latency_window: int = 4096
    hang_deadline_s: Optional[float] = 120.0


class _Replica:
    """Router-side view of one replica: endpoint, lease, piggybacked
    load, a small connection pool, and attribution stats."""

    def __init__(self, rid: int, endpoint: str, cfg: RouterConfig):
        self.id = rid
        self.endpoint = endpoint
        self.cfg = cfg
        self.mu = threading.Lock()
        self.healthy = True
        self.retired = False   # scale-down: health loop exits, no evict
        self.last_ok = time.monotonic()
        self.queue_depth = 0
        self.ewma_ms: Optional[float] = None
        self.inflight = 0
        # attribution (load_gen per-replica report)
        self.requests = 0
        self.failures = 0
        self.sheds = 0        # replica-reported overloads seen here
        self.lat_ms = collections.deque(maxlen=cfg.latency_window)
        self._free: List[RPCClient] = []
        self._gauge = _obs.registry().gauge(
            "router_replica_queue_depth", replica=str(rid))

    # -- connection pool ----------------------------------------------
    def acquire(self) -> RPCClient:
        with self.mu:
            if self._free:
                return self._free.pop()
        return RPCClient(
            self.endpoint,
            timeout_s=self.cfg.dispatch_connect_timeout_s,
            deadline_s=self.cfg.rpc_deadline_s,
            trainer_id=self.cfg.router_id)

    def release(self, client: RPCClient):
        with self.mu:
            if not self.retired:
                self._free.append(client)
                return
        # scale-down raced an in-flight dispatch: the pool is gone,
        # close instead of parking the socket on a dead replica view
        try:
            client.close()
        except Exception:
            pass

    def close_clients(self):
        with self.mu:
            free, self._free = self._free, []
        for c in free:
            try:
                c.close()
            except Exception:
                pass

    # -- load/lease ----------------------------------------------------
    def mark_ok(self, load: Optional[dict]):
        with self.mu:
            # ordered against remove_replica's retire+zero under the
            # same lock: a probe reply landing mid-retire must not
            # resurrect the gauge with the last live depth forever
            if self.retired:
                return
            self.last_ok = time.monotonic()
            if load:
                self.queue_depth = int(load.get("queue_depth") or 0)
                if load.get("ewma_ms") is not None:
                    self.ewma_ms = float(load["ewma_ms"])
            self._gauge.set(self.queue_depth)

    def score(self):
        with self.mu:
            return (self.queue_depth + self.inflight,
                    self.ewma_ms if self.ewma_ms is not None else 0.0,
                    self.id)

    def saturated(self) -> bool:
        with self.mu:
            return (self.queue_depth + self.inflight
                    >= self.cfg.shed_queue_depth)

    def snapshot(self) -> dict:
        with self.mu:
            lat = list(self.lat_ms)
            out = {"endpoint": self.endpoint, "healthy": self.healthy,
                   "requests": self.requests,
                   "failures": self.failures, "sheds": self.sheds,
                   "inflight": self.inflight,
                   "queue_depth": self.queue_depth,
                   "ewma_ms": self.ewma_ms,
                   "last_ok_age_s": round(
                       time.monotonic() - self.last_ok, 3)}
        arr = np.asarray(lat)
        for q in (50, 99):
            out["p%d_ms" % q] = round(
                float(np.percentile(arr, q)), 3) if arr.size else None
        return out


class _ReplicaGroup:
    """One sharded replica group: N member `_Replica`s forming a mesh,
    member 0 the executor. Healthy = EVERY member's lease is live."""

    def __init__(self, gid: int, members: List[_Replica]):
        self.id = gid
        self.members = members
        self.primary = members[0]
        # evicted-state memo so the health loops emit one
        # group_evicted per transition, not one per probe tick
        self.evicted = False

    def healthy(self) -> bool:
        return all(m.healthy for m in self.members)


class ServingRouter:
    """Fronts N replicas (``endpoints``) with least-loaded dispatch,
    shedding, lease-based eviction, transparent retry, and versioned
    hot-swap. API mirrors ``ServingEngine``. With
    ``config.group_size > 1`` the endpoints form sharded replica
    GROUPS and dispatch targets group executors (see RouterConfig)."""

    def __init__(self, endpoints, config: Optional[RouterConfig] = None,
                 metrics_port=None):
        self.config = config or RouterConfig()
        if self.config.policy not in ("least_loaded", "round_robin"):
            raise InvalidRequest("unknown routing policy %r"
                                 % self.config.policy)
        self._replicas = [
            _Replica(i, ep, self.config)
            for i, ep in enumerate(endpoints)]
        if not self._replicas:
            raise InvalidRequest("a router needs >= 1 replica endpoint")
        gs = max(1, int(self.config.group_size))
        if gs > 1 and len(self._replicas) % gs:
            raise InvalidRequest(
                "group_size=%d does not divide the %d endpoints — "
                "groups are consecutive endpoint runs"
                % (gs, len(self._replicas)))
        self._groups = [
            _ReplicaGroup(g, self._replicas[g * gs:(g + 1) * gs])
            for g in range(len(self._replicas) // gs)] if gs > 1 \
            else None
        self._group_of = {}
        if self._groups:
            for grp in self._groups:
                for m in grp.members:
                    self._group_of[m.id] = grp
        self._next_gid = len(self._groups) if self._groups else 0
        self._rr = itertools.count()
        self._pending = 0
        self._mu = threading.Lock()
        self._stopped = False
        self._pool = ThreadPoolExecutor(
            max_workers=self.config.max_concurrency,
            thread_name_prefix="serving-router")
        reg = _obs.registry()
        # registry counters are process-wide (several routers share
        # them in /metrics); the instance tallies back stats()
        self._m_requests = {o: reg.counter("router_requests_total",
                                           outcome=o)
                           for o in ("completed", "shed", "failed")}
        self._m_retries = reg.counter("router_retries_total")
        self._h_latency = reg.histogram("router_latency_seconds")
        self._counts = {"completed": 0, "shed": 0, "failed": 0,
                        "retries": 0, "group_evictions": 0,
                        "group_readmissions": 0}
        self.metrics_server = None
        if metrics_port is not None:
            self.metrics_server = _obs.start_metrics_server(
                port=metrics_port)
        # health plane: one bump per completed/failed request; pending
        # futures with a silent beacon = wedged dispatch machinery
        self._beacon = _obs.Beacon(
            "router_dispatch/%d" % self.config.router_id)
        self._health_watch = None
        if self.config.hang_deadline_s is not None:
            self._health_watch = _obs.get_watchdog().watch(
                "router_dispatch/%d" % self.config.router_id,
                beacon=self._beacon,
                deadline_s=self.config.hang_deadline_s,
                pending_fn=lambda: self._pending > 0)
        # lease monitors: one thread + dedicated client per replica
        # (PR 5's HeartbeatThread shape — a shared thread would park a
        # healthy replica's probe behind a dead one's connect stall)
        self._hb_stop = threading.Event()
        self._hb_threads = []
        self._next_rid = len(self._replicas)
        for r in self._replicas:
            self._start_health_thread(r)

    def _start_health_thread(self, r: "_Replica"):
        # prune exited monitors (retired replicas) so autoscale churn
        # can't grow this list for the life of the router
        self._hb_threads = [t for t in self._hb_threads
                            if t.is_alive()]
        t = threading.Thread(target=self._health_loop, args=(r,),
                             daemon=True,
                             name="router-health-%d" % r.id)
        t.start()
        self._hb_threads.append(t)

    # -- dynamic membership (control-plane scale actuation) -----------
    def add_replica(self, endpoint: str) -> int:
        """Admit one more replica endpoint into dispatch (the
        autoscaler's scale-up actuator; observability/control.py).
        Returns the new replica id. Grouped routers don't scale — a
        group is a mesh, not a unit you add one endpoint to."""
        if self._groups is not None:
            raise InvalidRequest(
                "add_replica on a grouped router (group_size=%d) — "
                "scale whole groups via spawn_fleet instead"
                % self.config.group_size)
        if self._stopped:
            raise EngineStopped("router is shut down")
        with self._mu:
            rid = self._next_rid
            self._next_rid += 1
        # construct outside the lock (the replica view registers a
        # registry gauge), then admit with an atomic list swap so
        # dispatch readers never see a torn list
        r = _Replica(rid, endpoint, self.config)
        with self._mu:
            self._replicas = self._replicas + [r]
        self._start_health_thread(r)
        _obs.emit("replica_added", replica=rid, endpoint=endpoint,
                  replicas=len(self._replicas))
        return rid

    def remove_replica(self, rid: int) -> dict:
        """Retire one replica from dispatch (scale-down actuator):
        new requests stop landing on it immediately; in-flight ones
        finish (inference is read-only). Returns its final snapshot
        so the caller can reap the process behind it."""
        if self._groups is not None:
            raise InvalidRequest("remove_replica on a grouped router")
        with self._mu:
            r = next((x for x in self._replicas if x.id == rid), None)
            if r is None:
                raise InvalidRequest("no replica %d to remove" % rid)
            self._replicas = [x for x in self._replicas
                              if x.id != rid]
        with r.mu:
            r.retired = True
            r.healthy = False
            # zero AND drop the gauge series: a retired replica must
            # not export its last live depth, and under respawn/scale
            # churn (monotonic rids) dead series would otherwise
            # accumulate in the registry forever. Under r.mu so it
            # cannot race a mark_ok mid-probe (which checks `retired`
            # under the same lock; the detached object is write-safe)
            r._gauge.set(0)
            _obs.registry().remove_series(
                "router_replica_queue_depth", replica=str(rid))
        snap = r.snapshot()
        r.close_clients()
        _obs.emit("replica_retired", replica=rid,
                  endpoint=r.endpoint,
                  replicas=len(self._replicas))
        return snap

    def add_group(self, endpoints) -> int:
        """Admit one WHOLE sharded replica group into dispatch — the
        grouped counterpart of ``add_replica`` and the unit
        ``FleetScaler`` group scale-up actuates. ``endpoints`` must be
        exactly ``group_size`` members in rank order (member 0 becomes
        the group executor). Admission is atomic: the group enters the
        dispatch set in one list swap, so a request either sees the
        full mesh or none of it — never a partial group."""
        if self._groups is None:
            raise InvalidRequest(
                "add_group on an ungrouped router — scale single "
                "replicas via add_replica instead")
        if self._stopped:
            raise EngineStopped("router is shut down")
        endpoints = list(endpoints)
        gs = int(self.config.group_size)
        if len(endpoints) != gs:
            raise InvalidRequest(
                "add_group needs exactly group_size=%d endpoints, "
                "got %d — a group is admitted whole or not at all"
                % (gs, len(endpoints)))
        with self._mu:
            rids = list(range(self._next_rid, self._next_rid + gs))
            self._next_rid += gs
            gid = self._next_gid
            self._next_gid += 1
        # construct outside the lock (gauge registration), then admit
        # with atomic swaps so dispatch never sees a partial group
        members = [_Replica(rid, ep, self.config)
                   for rid, ep in zip(rids, endpoints)]
        grp = _ReplicaGroup(gid, members)
        with self._mu:
            self._replicas = self._replicas + members
            self._groups = self._groups + [grp]
            for m in members:
                self._group_of[m.id] = grp
        for m in members:
            self._start_health_thread(m)
        _obs.emit("group_added", group=gid,
                  members=[m.id for m in members],
                  executor=grp.primary.id, groups=len(self._groups))
        return gid

    def remove_group(self, gid: int) -> dict:
        """Retire one whole replica group from dispatch (group
        scale-down actuator): the group leaves the dispatch set in one
        swap, every member is marked retired, and the final member
        snapshots come back so the caller can reap the processes."""
        if self._groups is None:
            raise InvalidRequest("remove_group on an ungrouped router")
        with self._mu:
            grp = next((g for g in self._groups if g.id == gid), None)
            if grp is None:
                raise InvalidRequest("no group %d to remove" % gid)
            if len(self._groups) <= 1:
                raise InvalidRequest(
                    "refusing to remove the last group — a router "
                    "needs >= 1 dispatch target")
            gone = {m.id for m in grp.members}
            self._groups = [g for g in self._groups if g.id != gid]
            self._replicas = [r for r in self._replicas
                              if r.id not in gone]
            for rid in gone:
                self._group_of.pop(rid, None)
        snaps = {}
        for m in grp.members:
            with m.mu:
                m.retired = True
                m.healthy = False
                m._gauge.set(0)
                _obs.registry().remove_series(
                    "router_replica_queue_depth", replica=str(m.id))
            snaps[str(m.id)] = m.snapshot()
            m.close_clients()
        _obs.emit("group_retired", group=gid,
                  members=sorted(gone), groups=len(self._groups))
        return snaps

    def _replica_by_id(self, rid: int) -> "_Replica":
        r = next((x for x in self._replicas if x.id == rid), None)
        if r is None:
            raise InvalidRequest("no replica %d" % rid)
        return r

    # -- pressure tap (control-plane autoscaling sensor) --------------
    def pressure(self) -> dict:
        """The autoscaler's sensor: live queue/latency pressure over
        the HEALTHY dispatch set. ``depth_per_replica`` is the scaling
        signal (reported batcher depth + local in-flight, averaged
        over healthy replicas); p99 comes from the replicas' recent
        latency windows."""
        healthy = self._healthy()
        depth = 0
        lat = []
        for r in healthy:
            with r.mu:
                depth += r.queue_depth + r.inflight
                lat.extend(list(r.lat_ms)[-256:])
        arr = np.asarray(lat)
        with self._mu:
            pending = self._pending
        return {
            "replicas": len(self._replicas),
            "healthy": len(healthy),
            "queue_depth": depth,
            "depth_per_replica": round(depth / len(healthy), 4)
            if healthy else float(pending),
            "pending": pending,
            "p99_ms": round(float(np.percentile(arr, 99)), 3)
            if arr.size else None,
        }

    # -- dispatch ------------------------------------------------------
    def _healthy(self) -> List[_Replica]:
        """Dispatchable targets: healthy replicas, or — under groups —
        the EXECUTORS of fully-healthy groups (a group with any member
        down is not a target even while its executor still answers)."""
        if self._groups is not None:
            return [g.primary for g in self._groups if g.healthy()]
        return [r for r in self._replicas if r.healthy]

    def _pick(self, tried) -> Optional[_Replica]:
        cands = [r for r in self._healthy() if r.id not in tried]
        if not cands:
            # every healthy replica already tried this request: allow
            # a second pass rather than failing early (the retry
            # budget still bounds total attempts)
            cands = self._healthy()
        if not cands:
            return None
        if self.config.policy == "round_robin":
            return cands[next(self._rr) % len(cands)]
        return min(cands, key=_Replica.score)

    def infer(self, feed: Dict[str, np.ndarray],
              model: Optional[str] = None,
              deadline_ms: Optional[float] = None):
        """Route one request; returns a Future resolving to the
        per-output list of arrays. ``ServerOverloaded`` (all replicas
        saturated / router pending cap) raises synchronously; replica
        failures surface through the Future as structured errors after
        the retry budget."""
        if self._stopped:
            raise EngineStopped("router is shut down")
        healthy = self._healthy()
        if healthy and all(r.saturated() for r in healthy):
            self._shed("all %d healthy replicas saturated (depth >= %d)"
                       % (len(healthy), self.config.shed_queue_depth))
        with self._mu:
            capped = self._pending >= self.config.max_pending
            if not capped:
                self._pending += 1
        if capped:
            self._shed("router pending cap %d reached"
                       % self.config.max_pending)
        feed = {k: np.asarray(v) for k, v in feed.items()}
        fut = self._pool.submit(self._run_request, model, feed,
                                deadline_ms)
        fut.add_done_callback(self._done_cb)
        return fut

    def _shed(self, why):
        self._m_requests["shed"].inc()
        with self._mu:
            self._counts["shed"] += 1
        _obs.emit("router_shed", reason=why)
        raise ServerOverloaded("router shedding: %s" % why, reason=why)

    def _retry_mark(self, replica_id, attempt, err):
        self._m_retries.inc()
        with self._mu:
            self._counts["retries"] += 1
        _obs.emit("router_retry", replica=replica_id, attempt=attempt,
                  error=repr(err))

    def _done_cb(self, fut):
        try:
            exc = fut.exception()
        except Exception:
            exc = None  # cancelled by the client
        outcome = "failed" if exc is not None else "completed"
        with self._mu:
            self._pending -= 1
            self._counts[outcome] += 1
        self._m_requests[outcome].inc()
        self._beacon.bump()

    def infer_sync(self, feed, model=None, deadline_ms=None,
                   timeout: Optional[float] = None):
        return self.infer(feed, model=model,
                          deadline_ms=deadline_ms).result(timeout)

    def _run_request(self, model, feed, deadline_ms):
        t0 = time.monotonic()
        deadline = t0 + deadline_ms / 1e3 if deadline_ms else None
        names = sorted(feed)
        arrays = [feed[n] for n in names]
        tried = set()
        last_err = None
        for attempt in range(self.config.max_retries + 1):
            if deadline is not None and time.monotonic() >= deadline:
                raise DeadlineExceeded(
                    "request deadline passed after %d dispatch "
                    "attempt(s)" % attempt, attempts=attempt)
            r = self._pick(tried)
            if r is None:
                raise ReplicaUnavailable(
                    "no healthy replicas (all %d evicted)"
                    % len(self._replicas),
                    replicas=len(self._replicas))
            remaining_ms = None if deadline is None else max(
                1.0, (deadline - time.monotonic()) * 1e3)
            payload = pack_blob({"inputs": names,
                                 "deadline_ms": remaining_ms}, arrays)
            rpc_deadline = self.config.rpc_deadline_s if deadline is \
                None else max(0.05, deadline - time.monotonic() + 1.0)
            try:
                client = r.acquire()
            except Exception as e:
                # fresh connect to a dead replica: a transport-level
                # miss like any other — try the next replica
                last_err = e
                tried.add(r.id)
                with r.mu:
                    r.failures += 1
                self._retry_mark(r.id, attempt, e)
                continue
            with r.mu:
                r.inflight += 1
            try:
                body = client.call("INFER", model or "", payload,
                                   deadline_s=rpc_deadline)
            except (RpcError, DeadlineExceededError) as e:
                last_err = e
                tried.add(r.id)
                with r.mu:
                    r.inflight -= 1
                    r.failures += 1
                r.release(client)
                self._retry_mark(r.id, attempt, e)
                continue
            except Exception:
                with r.mu:
                    r.inflight -= 1
                r.release(client)
                raise
            with r.mu:
                r.inflight -= 1
            r.release(client)
            meta, outs = unpack_blob(body)
            r.mark_ok(meta.get("load"))
            if not meta.get("ok"):
                err = _error_from_meta(meta)
                if isinstance(err, ServerOverloaded):
                    # THIS replica is full; another may not be — keep
                    # the request alive while budget remains
                    with r.mu:
                        r.sheds += 1
                    last_err = err
                    tried.add(r.id)
                    self._retry_mark(r.id, attempt, err)
                    continue
                raise err
            lat = time.monotonic() - t0
            with r.mu:
                r.requests += 1
                r.lat_ms.append(lat * 1e3)
            self._h_latency.observe(lat)
            return outs
        if isinstance(last_err, ServingError):
            raise last_err
        raise ReplicaUnavailable(
            "request failed on %d replicas within the retry budget: %r"
            % (len(tried) or 1, last_err), last_error=repr(last_err))

    # -- health / leases ----------------------------------------------
    def _health_loop(self, r: _Replica):
        # disjoint beat range per replica: trace_merge pairs
        # heartbeat_rtt/heartbeat_recv by (tid, beat) alone
        beat = (r.id + 1) * 1_000_000
        client = None
        interval = self.config.heartbeat_interval_s
        while not self._hb_stop.wait(interval):
            if r.retired:
                break  # scale-down: probe loop ends with the replica
            beat += 1
            try:
                # serving lease probe rides the fault-point plane: a
                # "drop" plan loses this beat (the eviction clock keeps
                # running — enough dropped beats and the lease expires
                # exactly like a dead replica), a "delay" stalls it
                _faults.faultpoint("serving.lease_probe",
                                   endpoint=r.endpoint, replica=r.id)
                if client is None:
                    client = RPCClient(
                        r.endpoint,
                        timeout_s=max(0.2, interval),
                        deadline_s=max(0.2, self.config.lease_timeout_s
                                       / 2.0),
                        trainer_id=self.config.router_id)
                t0 = time.time()
                body = client.call("HEARTBEAT", seq=beat)
                t1 = time.time()
                _obs.emit("heartbeat_rtt", endpoint=r.endpoint,
                          beat=beat, tid=self.config.router_id,
                          t0_wall=t0, t1_wall=t1,
                          rtt_s=round(t1 - t0, 6))
                load = None
                if body:
                    try:
                        meta, _ = unpack_blob(body)
                        load = meta.get("load")
                    except Exception:
                        pass
                r.mark_ok(load)
                with r.mu:
                    # atomic vs remove_replica's retire: a heartbeat
                    # that raced the retire must not flip the replica
                    # back healthy and forge a replica_readmitted for
                    # a component that just left the fleet
                    readmit = not r.healthy and not r.retired
                    if readmit:
                        r.healthy = True
                if readmit:
                    _obs.emit("replica_readmitted", replica=r.id,
                              endpoint=r.endpoint)
                    self._note_group_transition(r)
            except Exception:
                if client is not None:
                    try:
                        client.close()
                    except Exception:
                        pass
                    client = None
                if r.healthy and (time.monotonic() - r.last_ok
                                  > self.config.lease_timeout_s):
                    r.healthy = False
                    _obs.emit(
                        "replica_evicted", replica=r.id,
                        endpoint=r.endpoint,
                        lease_timeout_s=self.config.lease_timeout_s)
                    self._note_group_transition(r, cause=r.id)
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    def _note_group_transition(self, r: _Replica, cause=None):
        """After one member's health flipped: emit the whole-group
        eviction/readmission transition (once per edge). A group is a
        mesh — losing ANY host loses the executable, so the group
        leaves the dispatch set as one unit and comes back as one.
        The edge detection (read + flip of ``grp.evicted``) happens
        under ``self._mu``: each group has one health thread PER
        member, and two members lapsing in the same heartbeat window
        must still produce exactly one transition. The journal emit
        stays outside the lock (lock_lint's emit-under-lock rule)."""
        if self._groups is None:
            return
        grp = self._group_of.get(r.id)
        if grp is None:
            return
        healthy = grp.healthy()
        edge = None
        with self._mu:
            if not healthy and not grp.evicted:
                grp.evicted = True
                self._counts["group_evictions"] += 1
                edge = "group_evicted"
            elif healthy and grp.evicted:
                grp.evicted = False
                self._counts["group_readmissions"] += 1
                edge = "group_readmitted"
        if edge == "group_evicted":
            _obs.emit("group_evicted", group=grp.id,
                      members=[m.id for m in grp.members],
                      cause_member=cause,
                      executor=grp.primary.id)
        elif edge == "group_readmitted":
            _obs.emit("group_readmitted", group=grp.id,
                      members=[m.id for m in grp.members])

    # -- control-plane helpers ----------------------------------------
    def _ctrl(self, r: _Replica, meta: dict, deadline_s=120.0) -> dict:
        client = r.acquire()
        try:
            body = client.call("CTRL", "", pack_blob(meta),
                               deadline_s=deadline_s)
        finally:
            r.release(client)
        out, _ = unpack_blob(body)
        if not out.get("ok"):
            raise _error_from_meta(out)
        return out

    def replica_stats(self, rid: int) -> dict:
        return self._ctrl(self._replica_by_id(rid),
                          {"op": "stats"})["stats"]

    # -- versioned hot-swap -------------------------------------------
    def swap_model(self, model_dir: str, model: str = "default",
                   version: Optional[str] = None,
                   drain_timeout_s: float = 60.0) -> dict:
        """Hot-swap ``model`` to the version saved at ``model_dir``
        across every healthy replica: signature-compat gate -> load +
        warm v2 next to v1 -> atomically flip admissions -> drain and
        unload v1. No request fails because of the flip; a v2 that
        would break v1 clients is refused before any replica loads
        it."""
        healthy = self._healthy()
        if not healthy:
            raise ReplicaUnavailable("no healthy replicas to swap on")
        first = healthy[0]
        cur = self._ctrl(first, {"op": "signature", "model": model})
        old_version, old_sig = cur["version"], cur["signature"]
        sig_path = os.path.join(str(model_dir), SIGNATURE_FILENAME)
        if not os.path.exists(sig_path):
            raise SignatureMismatch(
                "no %s sidecar in %r — hot-swap needs the saved "
                "signature to prove v2 serves v1 clients; re-save the "
                "model with save_inference_model" % (SIGNATURE_FILENAME,
                                                     model_dir),
                model=model, model_dir=str(model_dir))
        with open(sig_path) as f:
            new_sig = json.load(f)
        problems = signature_compat(old_sig, new_sig)
        if problems:
            raise SignatureMismatch(
                "hot-swap %s %s -> %s refused — the new signature "
                "breaks live clients:\n  - %s\nFix the saved model "
                "(or serve it under a NEW model name so clients opt "
                "in)" % (model, old_version, model_dir,
                         "\n  - ".join(problems)),
                model=model, problems=problems)
        if version is None:
            nums = [int(v[1:]) for r in healthy
                    for v in (self.replica_stats(r.id)["models"]
                              .get(model, {}).get("versions", []))
                    if v.startswith("v") and v[1:].isdigit()]
            version = "v%d" % (max(nums or [0]) + 1)
        report = {"model": model, "from": old_version, "to": version,
                  "replicas": [r.id for r in healthy]}
        # 1) load + warm everywhere (abort-and-unload on any failure:
        #    admissions never flip to a partially-loaded fleet)
        loaded, warmed = [], {}
        try:
            for r in healthy:
                out = self._ctrl(r, {"op": "load_version",
                                     "model": model,
                                     "version": version,
                                     "model_dir": str(model_dir)})
                loaded.append(r)
                warmed[r.id] = out.get("warmed_buckets", [])
                if not warmed[r.id]:
                    raise ServingError(
                        "replica %d loaded %s/%s but warmed no "
                        "buckets — refusing to admit cold-compile "
                        "traffic" % (r.id, model, version))
        except Exception:
            for r in loaded:
                try:
                    self._ctrl(r, {"op": "drain_unload",
                                   "model": model, "version": version,
                                   "timeout_s": drain_timeout_s})
                except Exception:
                    pass
            raise
        report["warmed_buckets"] = warmed
        _obs.emit("model_swap_loaded", model=model, version=version,
                  replicas=[r.id for r in healthy])
        # 2) flip admissions (per replica the flip is atomic; across
        #    replicas it is eventually-uniform within one pass)
        for r in healthy:
            self._ctrl(r, {"op": "flip", "model": model,
                           "version": version})
        _obs.emit("model_swap_flipped", model=model, version=version,
                  previous=old_version)
        # 3) drain + unload the predecessor
        for r in healthy:
            self._ctrl(r, {"op": "drain_unload", "model": model,
                           "version": old_version,
                           "timeout_s": drain_timeout_s},
                       deadline_s=drain_timeout_s + 30.0)
        _obs.emit("model_swap_complete", model=model,
                  version=version, drained=old_version)
        return report

    # -- introspection / lifecycle ------------------------------------
    def stats(self) -> dict:
        with self._mu:
            pending = self._pending
            counts = dict(self._counts)
        out = {
            "router": dict(counts, policy=self.config.policy,
                           pending=pending),
            "replicas": {str(r.id): r.snapshot()
                         for r in self._replicas},
        }
        if self._groups is not None:
            out["groups"] = {
                str(g.id): {"members": [m.id for m in g.members],
                            "executor": g.primary.id,
                            "healthy": g.healthy()}
                for g in self._groups}
        return out

    def models(self):
        for r in self._healthy():
            try:
                return sorted(self.replica_stats(r.id)["models"])
            except Exception:
                continue
        return []

    def shutdown(self, timeout: Optional[float] = 10.0):
        self._stopped = True
        if self._health_watch is not None:
            _obs.get_watchdog().unwatch(self._health_watch)
            self._health_watch = None
        self._hb_stop.set()
        for t in self._hb_threads:
            t.join(timeout=timeout)
        self._pool.shutdown(wait=True)
        for r in self._replicas:
            r.close_clients()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
