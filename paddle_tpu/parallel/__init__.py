"""Distributed execution over device meshes.

Replaces the reference's three distributed stacks (SURVEY §2.4):
  - in-graph collectives / ParallelExecutor  -> sharding annotations +
    GSPMD-inserted collectives over ICI (compiler.py + mesh.py)
  - DistributeTranspiler + gRPC parameter server -> ZeRO-style sharded
    params/optimizer state (BuildStrategy.ReduceStrategy.Reduce); a
    host-side table service is only needed for >HBM embeddings
  - fleet/PSLib sparse PS -> sharded embedding tables + all-to-all
    (parallel.sparse)

Multi-host: jax.distributed.initialize + the same mesh spanning all
processes (the analog of NCCL2-mode trainer ranks, gen_nccl_id_op.cc).
"""

from . import mesh  # noqa: F401
from .mesh import (current_mesh, data_parallel_mesh, make_mesh,  # noqa
                   mesh_guard, named_sharding, set_mesh,
                   shard_batch_spec)
from .api import shard, replicate  # noqa: F401
from . import collectives  # noqa: F401
from .collectives import (all_reduce_exact, all_reduce_q8,  # noqa: F401
                          all_gather_params, all_gather_params_q8,
                          ensure_sharded_state, grad_bytes_per_step,
                          reduce_scatter_gather, reduce_scatter_shard,
                          reduce_scatter_shard_q8, slot_bytes_per_chip)
from . import ring_attention  # noqa: F401  (registers the op)
from . import ulysses  # noqa: F401  (registers the op)
from .ring_attention import ring_attention as ring_attention_fn  # noqa
from .ulysses import ulysses_attention as ulysses_attention_fn  # noqa
from .ulysses import sequence_parallel_attention  # noqa: F401
from . import multihost  # noqa: F401
from . import pipeline  # noqa: F401
from .pipeline import gpipe_apply, stack_stage_params  # noqa: F401
from . import moe  # noqa: F401
from .moe import moe_ffn, moe_ffn_reference  # noqa: F401
from . import zigzag  # noqa: F401
from .zigzag import zigzag_attention  # noqa: F401
