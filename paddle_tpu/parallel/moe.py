"""Expert parallelism: Switch-style mixture-of-experts FFN over an
``ep`` mesh axis.

Not in the 2019 reference — the last cell of this framework's
parallelism matrix (dp x tp x sp x pp x ep), built the TPU way
(GShard/Switch): static shapes throughout (capacity buckets, no
data-dependent shapes under jit), expert weights sharded over ``ep``,
tokens data-sharded over the SAME axis, and ONE ``lax.all_to_all``
each way moving only the capacity buckets across ICI.

Routing (``top_k``): 1 = Switch (default), 2 = GShard top-2 with
renormalized gates, secondaries queueing behind all primaries of the
same expert. Capacity C = ceil(n * top_k * capacity_factor / E);
tokens beyond it are DROPPED (zero contribution) — the standard
static-shape trade; callers size capacity_factor accordingly. The
aux balancing loss is returned so training can regularize routing
(Switch Transformer recipe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from ..ops.registry import register
from . import mesh as mesh_lib


def _route(x, gate_w, n_experts, capacity, top_k):
    """Shared routing math, identical on the sharded and reference
    paths (determinism is the equality test's foundation). top_k=1 is
    Switch (raw top-1 gate prob); top_k=2 is GShard (gates
    renormalized over the two chosen experts, secondary tokens
    queueing behind ALL primary tokens of the same expert so the
    second choice drops first under pressure). Returns
    (dispatch [E, C, D], combines: list of (gate, idx, pos, keep),
    f [E] primary routed fraction, p [E] mean router prob). The aux
    loss is E * sum(f * p) — composed by the CALLER so the sharded
    path can pmean f and p across shards BEFORE the product."""
    n, d = x.shape
    logits = x @ gate_w
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)
    p1 = jnp.max(probs, axis=-1)
    oh1 = jax.nn.one_hot(idx1, n_experts, dtype=jnp.float32)
    pos1 = (jnp.cumsum(oh1, axis=0) * oh1).sum(-1) - 1.0
    if top_k == 2:
        masked = probs - oh1 * probs
        idx2 = jnp.argmax(masked, axis=-1)
        p2 = jnp.max(masked, axis=-1)
        oh2 = jax.nn.one_hot(idx2, n_experts, dtype=jnp.float32)
        denom = jnp.maximum(p1 + p2, 1e-9)
        pos2 = ((jnp.cumsum(oh2, axis=0) * oh2).sum(-1) - 1.0
                + oh1.sum(0)[idx2])
        choices = [(p1 / denom, idx1, pos1), (p2 / denom, idx2, pos2)]
    else:
        choices = [(p1, idx1, pos1)]
    combines = []
    dispatch = jnp.zeros((n_experts, capacity, d), x.dtype)
    for g, idx, posf in choices:
        pos = posf.astype(jnp.int32)
        keep = (pos < capacity) & (pos >= 0)
        contrib = jnp.where(keep[:, None], x, 0.0)
        dispatch = dispatch.at[
            idx, jnp.clip(pos, 0, capacity - 1)].add(contrib)
        combines.append((g, idx, pos, keep))
    return dispatch, combines, oh1.mean(0), probs.mean(0)


def _expert_ffn(w1, b1, w2, b2, h):
    """Batched per-expert FFN: h [E_loc, T, D] -> [E_loc, T, D]."""
    y = jnp.einsum("etd,edf->etf", h, w1) + b1[:, None, :]
    y = jax.nn.relu(y)
    return jnp.einsum("etf,efd->etd", y, w2) + b2[:, None, :]


def _combine2(expert_out, combines, capacity):
    """Gather each choice's expert output, scale by its gate, sum;
    dropped tokens contribute zero."""
    out = 0.0
    for g, idx, pos, keep in combines:
        out = out + jnp.where(
            keep[:, None],
            expert_out[idx, jnp.clip(pos, 0, capacity - 1)]
            * g[:, None].astype(expert_out.dtype), 0.0)
    return out


def moe_ffn_reference(x, gate_w, w1, b1, w2, b2, *,
                      capacity_factor=1.25, top_k=1):
    """Single-device reference semantics (the equality oracle): same
    routing, all experts local."""
    if top_k not in (1, 2):
        raise ValueError("top_k must be 1 (Switch) or 2 (GShard), "
                         "got %r" % (top_k,))
    n = x.shape[0]
    E = w1.shape[0]
    capacity = int(-(-n * top_k * capacity_factor // E))
    dispatch, combines, f, p = _route(x, gate_w, E, capacity, top_k)
    aux = E * jnp.sum(f * p)
    expert_out = _expert_ffn(w1, b1, w2, b2, dispatch)
    return _combine2(expert_out, combines, capacity), aux


def moe_ffn(x, gate_w, w1, b1, w2, b2, *, mesh=None, axis="ep",
            capacity_factor=1.25, top_k=1):
    """Expert-parallel MoE FFN. x [N, D] tokens (sharded over the ep
    axis by the shard_map in_specs); gate_w [D, E] replicated; expert
    weights w1 [E, D, F], b1 [E, F], w2 [E, F, D], b2 [E, D] sharded
    over ep on their leading E axis. Returns ([N, D], aux_loss).

    Per shard: route local tokens to ALL experts into capacity
    buckets, all_to_all the buckets so each device holds ITS experts'
    tokens from every shard, run the batched expert FFN, all_to_all
    back, combine. The aux loss is the GLOBAL Switch loss (fractions
    pmean'd across shards before the product).

    Capacity semantics under pressure: buckets are sized and filled
    PER TOKEN SHARD (C = ceil(N/ep * cf / E), the GShard/Switch
    static-shape discipline — dropping is a local decision, no global
    sort). A skewed shard can therefore drop tokens the single-device
    reference (global buckets) would keep: with no drops the two
    paths are exactly equal (the tested contract); under capacity
    pressure they legitimately differ. Size capacity_factor for the
    no-drop regime or accept shard-local dropping, as on any ep
    pod."""
    from jax.experimental.shard_map import shard_map

    if top_k not in (1, 2):
        raise ValueError("top_k must be 1 (Switch) or 2 (GShard), "
                         "got %r" % (top_k,))
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return moe_ffn_reference(x, gate_w, w1, b1, w2, b2,
                                 capacity_factor=capacity_factor,
                                 top_k=top_k)

    ep = mesh.shape[axis]
    E = w1.shape[0]
    if E % ep != 0:
        raise ValueError("num experts %d not divisible by ep=%d"
                         % (E, ep))
    if x.shape[0] % ep != 0:
        raise ValueError("token count %d not divisible by ep=%d"
                         % (x.shape[0], ep))
    n_loc = x.shape[0] // ep
    capacity = int(-(-n_loc * top_k * capacity_factor // E))

    def body(x_l, gate_w, w1_l, b1_l, w2_l, b2_l):
        dispatch, combines, f, p = _route(
            x_l, gate_w, E, capacity, top_k)          # [E, C, D]
        # [E, C, D] -> [E/ep, ep*C, D]: each device receives its
        # experts' buckets from every token shard
        h = lax.all_to_all(dispatch, axis, split_axis=0,
                           concat_axis=1, tiled=True)
        out = _expert_ffn(w1_l, b1_l, w2_l, b2_l, h)
        # route the processed buckets back to their token shards
        back = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                              tiled=True)             # [E, C, D]
        y = _combine2(back, combines, capacity)
        # GLOBAL Switch loss: average the fractions across shards
        # first, then take the product (shards are equal-sized, so
        # pmean(f) is the global routed fraction exactly)
        aux = E * jnp.sum(lax.pmean(f, axis) * lax.pmean(p, axis))
        return y, aux

    tok = PartitionSpec(axis)
    exp = PartitionSpec(axis)
    f = shard_map(
        body, mesh=mesh,
        in_specs=(tok, PartitionSpec(), exp, exp, exp, exp),
        out_specs=(tok, PartitionSpec()),
        check_rep=False)
    return f(x, gate_w, w1, b1, w2, b2)


@register("moe_ffn", ["X", "GateW", "W1", "B1", "W2", "B2"],
          ["Out", "AuxLoss"])
def moe_ffn_op(x, gate_w, w1, b1, w2, b2, *, capacity_factor=1.25,
               axis="ep", top_k=1):
    """Static-graph op twin (the ring_attention_op pattern): uses the
    ambient mesh set by CompiledProgram.run / mesh_guard; without an
    ep axis in scope it falls back to the single-device reference."""
    return moe_ffn(x, gate_w, w1, b1, w2, b2, axis=axis,
                   capacity_factor=capacity_factor, top_k=top_k)
