"""Ulysses-style sequence parallelism: all-to-all head/sequence
re-sharding around full attention.

Like ring attention (ring_attention.py), this is a new TPU-first
capability with no 2019-reference counterpart (SURVEY §5
"long-context"). Where the ring rotates K/V blocks with ppermute (N-1
ICI hops, compute overlapped), Ulysses re-shards ONCE each way:

    [B, H, S/n, Dh]  --all_to_all-->  [B, H/n, S, Dh]
       (sequence-sharded)                (head-sharded)

each device then runs ordinary full attention for its heads (any
kernel — including the pallas flash path — since the sequence is whole
again), and a second all-to-all restores sequence sharding. Two
collectives total, so it wins over the ring when heads divide evenly
and S^2/n attention fits per device; the ring wins for extreme S.
Both compose with dp/tp via the mesh axes.

Requires num_heads % sp == 0 (the classic Ulysses constraint).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from ..core.enforce import enforce
from ..ops.registry import register
from . import mesh as mesh_lib

_NEG = -1.0e30


def _full_attention(q, k, v, scale, causal, bias=None):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + lax.stop_gradient(bias).astype(jnp.float32)
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        q_pos = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


import threading

# recursion guard: _attend re-enters the scaled_dot_product_attention
# lowering INSIDE the shard_map body; that lowering's sp routing must
# see it is already under a sequence-parallel schedule (the local
# H/n, S shapes can look routable again) and keep its per-device path
_SP_BODY = threading.local()


def in_sp_body() -> bool:
    return getattr(_SP_BODY, "active", False)


def _attend(q, k, v, bias, scale, causal):
    """Per-device attention after the re-shard — dispatched through
    the op registry so FLAGS_op_library=pallas gets the FLASH kernel
    (O(S*Dh) residuals, no S^2 score matrix in HBM) exactly as the
    module docstring promises; the base library takes the jnp path."""
    from ..core.flags import FLAGS
    from ..ops.registry import get as get_op
    opdef = get_op("scaled_dot_product_attention")
    fn = opdef.pick(FLAGS.op_library or None)
    _SP_BODY.active = True
    try:
        return fn(q, k, v, bias, scale=scale, causal=causal,
                  is_test=True)
    finally:
        _SP_BODY.active = False


def ulysses_attention_inner(q, k, v, bias=None, *, axis_name,
                            scale=1.0, causal=False):
    """Per-shard body (inside shard_map): q,k,v local
    [B, H, S/n, Dh] → all-to-all → full attention on H/n heads →
    all-to-all back. ``bias`` (additive attention bias, replicated —
    every device holds the full [B, 1|H, Sq, Sk]) slices its HEAD dim
    when it carries one, since after the re-shard each device attends
    H/n heads against the whole sequence."""
    # seq-sharded → head-sharded: split heads across the axis, gather
    # the full sequence
    q = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    if bias is not None and bias.shape[1] > 1:
        # per-head bias: this device now holds heads
        # [idx*H/n, (idx+1)*H/n) — slice the matching bias rows
        h_loc = q.shape[1]
        idx = lax.axis_index(axis_name)
        bias = lax.dynamic_slice_in_dim(bias, idx * h_loc, h_loc,
                                        axis=1)
    out = _attend(q, k, v, bias, scale, causal)
    # head-sharded → seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q, k, v, mesh=None, axis="sp", scale=1.0,
                      causal=False, bias=None):
    """Global-view entry: q,k,v [B, H, S, Dh]; the shard_map in_specs
    shard the sequence over ``axis``. ``bias``: optional additive
    attention bias [B, 1|H, Sq, Sk] (pad masks, ALiBi) — replicated
    across the axis, exactly once per device, so the per-head math is
    identical to full attention. Falls back to plain fused attention
    when no sp axis is in scope (same contract as ring_attention)."""
    from jax.experimental.shard_map import shard_map

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return _full_attention(q, k, v, scale, causal, bias=bias)
    n = mesh.shape[axis]
    enforce(q.shape[1] % n == 0,
            "ulysses needs num_heads (%d) divisible by the sp degree "
            "(%d); use ring_attention otherwise", q.shape[1], n)
    spec = PartitionSpec(None, None, axis, None)
    body = functools.partial(ulysses_attention_inner, axis_name=axis,
                             scale=scale, causal=causal)
    if bias is None:
        f = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec, check_rep=False)
        return f(q, k, v)
    bias = lax.stop_gradient(bias)
    f = shard_map(body, mesh=mesh,
                  in_specs=(spec, spec, spec, PartitionSpec()),
                  out_specs=spec, check_rep=False)
    return f(q, k, v, bias)


@register("ulysses_attention", ["Q", "K", "V", "Bias"], ["Out"],
          nondiff=("Bias",))
def ulysses_attention_op(q, k, v, bias=None, *, scale=1.0,
                         causal=False, axis="sp"):
    """Static-graph op twin (uses the ambient mesh, like the
    ring_attention op)."""
    return ulysses_attention(q, k, v, axis=axis, scale=scale,
                             causal=causal, bias=bias)


# ---------------------------------------------------------------------------
# production routing: the compiler's sp dispatch
# ---------------------------------------------------------------------------

def sequence_parallel_attention(q, k, v, bias=None, scale=1.0,
                                causal=False, mesh=None, axis="sp"):
    """Route one attention through the sequence-parallel schedule the
    geometry admits, or return None when no sp path applies (the
    caller keeps its replicated lowering).

    This is the ONE routing decision `CompiledProgram` mesh runs make:
    the `scaled_dot_product_attention` base lowering calls it under the
    ambient mesh (`mesh_guard` installed by CompiledProgram.run), so a
    model built from ordinary layers engages zigzag/Ulysses the moment
    its BuildStrategy mesh carries an sp axis — no model changes.

      - causal, no bias, S divisible by 2·sp → **zigzag ring**
        (balanced causal schedule, flash chunk-pair kernels when the
        geometry fits);
      - heads divisible by sp, S divisible by sp → **Ulysses**
        all-to-all head re-sharding (bias rides replicated);
      - anything else → None (replicated full attention stays
        correct; GSPMD places it).

    Dropout never routes: the sp bodies run their per-device kernels
    with ``is_test=True``, and a mask drawn per-shard would break the
    dp-equality contract (docs/parallel.md)."""
    if in_sp_body():
        return None
    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return None
    if getattr(q, "ndim", 0) != 4 or k.ndim != 4:
        return None
    n = mesh.shape[axis]
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    if causal and bias is None and Sq == Sk and Sq % (2 * n) == 0:
        from .zigzag import zigzag_attention
        return zigzag_attention(q, k, v, mesh=mesh, axis=axis,
                                scale=scale)
    if H % n == 0 and Sq % n == 0 and Sk % n == 0:
        if bias is not None and bias.ndim == 4 \
                and bias.shape[1] not in (1, H):
            return None
        return ulysses_attention(q, k, v, mesh=mesh, axis=axis,
                                 scale=scale, causal=causal, bias=bias)
    return None
