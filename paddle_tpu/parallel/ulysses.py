"""Ulysses-style sequence parallelism: all-to-all head/sequence
re-sharding around full attention.

Like ring attention (ring_attention.py), this is a new TPU-first
capability with no 2019-reference counterpart (SURVEY §5
"long-context"). Where the ring rotates K/V blocks with ppermute (N-1
ICI hops, compute overlapped), Ulysses re-shards ONCE each way:

    [B, H, S/n, Dh]  --all_to_all-->  [B, H/n, S, Dh]
       (sequence-sharded)                (head-sharded)

each device then runs ordinary full attention for its heads (any
kernel — including the pallas flash path — since the sequence is whole
again), and a second all-to-all restores sequence sharding. Two
collectives total, so it wins over the ring when heads divide evenly
and S^2/n attention fits per device; the ring wins for extreme S.
Both compose with dp/tp via the mesh axes.

Requires num_heads % sp == 0 (the classic Ulysses constraint).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from ..core.enforce import enforce
from ..ops.registry import register
from . import mesh as mesh_lib

_NEG = -1.0e30


def _full_attention(q, k, v, scale, causal):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        q_pos = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


def _attend(q, k, v, scale, causal):
    """Per-device attention after the re-shard — dispatched through
    the op registry so FLAGS_op_library=pallas gets the FLASH kernel
    (O(S*Dh) residuals, no S^2 score matrix in HBM) exactly as the
    module docstring promises; the base library takes the jnp path."""
    from ..core.flags import FLAGS
    from ..ops.registry import get as get_op
    opdef = get_op("scaled_dot_product_attention")
    fn = opdef.pick(FLAGS.op_library or None)
    return fn(q, k, v, None, scale=scale, causal=causal, is_test=True)


def ulysses_attention_inner(q, k, v, *, axis_name, scale=1.0,
                            causal=False):
    """Per-shard body (inside shard_map): q,k,v local
    [B, H, S/n, Dh] → all-to-all → full attention on H/n heads →
    all-to-all back."""
    # seq-sharded → head-sharded: split heads across the axis, gather
    # the full sequence
    q = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    out = _attend(q, k, v, scale, causal)
    # head-sharded → seq-sharded
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q, k, v, mesh=None, axis="sp", scale=1.0,
                      causal=False):
    """Global-view entry: q,k,v [B, H, S, Dh]; the shard_map in_specs
    shard the sequence over ``axis``. Falls back to plain fused
    attention when no sp axis is in scope (same contract as
    ring_attention)."""
    from jax.experimental.shard_map import shard_map

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return _full_attention(q, k, v, scale, causal)
    n = mesh.shape[axis]
    enforce(q.shape[1] % n == 0,
            "ulysses needs num_heads (%d) divisible by the sp degree "
            "(%d); use ring_attention otherwise", q.shape[1], n)
    spec = PartitionSpec(None, None, axis, None)
    f = shard_map(
        functools.partial(ulysses_attention_inner, axis_name=axis,
                          scale=scale, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return f(q, k, v)


@register("ulysses_attention", ["Q", "K", "V"], ["Out"])
def ulysses_attention_op(q, k, v, *, scale=1.0, causal=False,
                         axis="sp"):
    """Static-graph op twin (uses the ambient mesh, like the
    ring_attention op)."""
    return ulysses_attention(q, k, v, axis=axis, scale=scale,
                             causal=causal)
