"""Ring attention: sequence/context parallelism over the ``sp`` mesh
axis.

Not present in the 2019 reference (SURVEY §5 "long-context") — this is
a new TPU-first capability: sequences longer than one chip's HBM are
sharded over the mesh's ``sp`` axis; each device holds a query block
and the key/value blocks rotate around the ring with
``lax.ppermute`` (one ICI hop per step) while a numerically-stable
online softmax accumulates the attention output. Compute for block i
overlaps the transfer of block i+1 (XLA schedules the ppermute ahead),
so the ring cost hides behind the matmuls at transformer scale.

Composable three ways:
  - pure function ``ring_attention(q, k, v, ...)`` over globally
    sharded arrays (shard_map under the hood);
  - registered op ``ring_attention`` for static Programs (falls back
    to single-device fused attention when no sp axis is in scope);
  - inside user shard_map code via ``ring_attention_inner``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..ops.registry import register
from . import mesh as mesh_lib

_NEG = -1.0e30


def ring_attention_inner(q, k, v, *, axis_name, n_blocks, scale=1.0,
                         causal=False, bias_blk=None):
    """Per-shard body (call inside shard_map/pmap). q,k,v: local
    [B, H, S_loc, Dh] blocks of the sequence-sharded arrays."""
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    my = jax.lax.axis_index(axis_name)

    m = jnp.full((B, H, Sq, 1), _NEG, jnp.float32)
    l = jnp.zeros((B, H, Sq, 1), jnp.float32)
    acc = jnp.zeros((B, H, Sq, Dh), jnp.float32)
    perm = [(j, (j + 1) % n_blocks) for j in range(n_blocks)]

    q32 = q.astype(jnp.float32)
    for step in range(n_blocks):
        src = (my - step) % n_blocks  # whose k/v block we hold now
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k.astype(jnp.float32)) * scale
        if bias_blk is not None:
            s = s + bias_blk
        if causal:
            q_pos = my * Sq + jax.lax.broadcasted_iota(
                jnp.int32, (Sq, Sk), 0)
            k_pos = src * Sk + jax.lax.broadcasted_iota(
                jnp.int32, (Sq, Sk), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        m = m_new
        if step != n_blocks - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    out = acc / jnp.maximum(l, 1e-20)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention_inner_flash(q, k, v, axis_name, n_blocks, scale,
                               causal):
    """Flash ring body: per-hop scores stay in VMEM (ops/pallas/
    ring.py kernels); only the O(Sq*Dh) online-softmax rescale and
    the [.., Sq] stats touch HBM per hop."""
    out, _ = _ring_flash_fwd(q, k, v, axis_name, n_blocks, scale,
                             causal)
    return out


def _ring_flash_fwd(q, k, v, axis_name, n_blocks, scale, causal):
    from ..ops.pallas import ring as R
    from .zigzag import online_merge_nk

    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_blocks) for j in range(n_blocks)]
    k0, v0 = k, v

    m = jnp.full((B, H, Sq), -1.0e30, jnp.float32)
    l = jnp.zeros((B, H, Sq), jnp.float32)
    acc = jnp.zeros((B, H, Sq, Dh), jnp.float32)
    for step in range(n_blocks):
        src = (my - step) % n_blocks
        pv, mb, lb = R.fwd_block(q, k, v, my * Sq, src * Sk, scale,
                                 causal)
        acc, m, l = online_merge_nk(acc, m, l, pv, mb, lb)
        if step != n_blocks - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    l_safe = jnp.maximum(l, 1e-20)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, (q, k0, v0, out, lse)


def _ring_flash_bwd(axis_name, n_blocks, scale, causal, res, g):
    from ..ops.pallas import ring as R

    q, k, v, out, lse = res
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_blocks) for j in range(n_blocks)]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dq = jnp.zeros((B, H, Sq, Dh), jnp.float32)
    dk_acc = jnp.zeros((B, H, Sk, Dh), jnp.float32)
    dv_acc = jnp.zeros((B, H, Sk, Dh), jnp.float32)
    # dk/dv accumulators TRAVEL WITH their k/v block: each device adds
    # its hop's contribution, then the 4-tuple rotates. After n
    # permutes (one per hop, INCLUDING the last) block b's accumulator
    # has every device's contribution and is back home at device b.
    for step in range(n_blocks):
        src = (my - step) % n_blocks
        dq_b, dk_b, dv_b = R.bwd_block(q, k, v, g, lse, delta,
                                       my * Sq, src * Sk, scale,
                                       causal)
        dq = dq + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        if step != n_blocks - 1:
            # k/v are never read after the last hop — only the
            # accumulators need the final rotation home
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


ring_attention_inner_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, mesh=None, axis="sp", scale=1.0,
                   causal=False, use_flash=None):
    """Global-view entry: q,k,v [B, H, S, Dh] (sharded or not — the
    shard_map in_specs place them on the sp axis). use_flash:
    None = auto (pallas hop kernels when the geometry fits and
    FLAGS.ring_flash is on); False forces the jnp body."""
    from jax.experimental.shard_map import shard_map

    from ..core.flags import FLAGS
    from ..ops.pallas import ring as R
    from .ulysses import _full_attention

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # no sequence axis in scope: plain fused attention (shared
        # with the ulysses fallback so the numerics can't diverge)
        return _full_attention(q, k, v, scale, causal)

    n = mesh.shape[axis]
    B, H, S, Dh = q.shape
    if use_flash is None:
        use_flash = (FLAGS.ring_flash
                     and S % n == 0
                     and R.applicable(B, H, S // n, S // n, Dh,
                                      q.dtype.itemsize))
    spec = PartitionSpec(None, None, axis, None)
    if use_flash:
        # custom_vjp nondiff args must be POSITIONAL
        def body(q_, k_, v_):
            return ring_attention_inner_flash(q_, k_, v_, axis, n,
                                              scale, causal)
    else:
        body = functools.partial(ring_attention_inner, axis_name=axis,
                                 n_blocks=n, scale=scale,
                                 causal=causal)
    f = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return f(q, k, v)


@register("ring_attention", ["Q", "K", "V"], ["Out"])
def ring_attention_op(q, k, v, *, scale=1.0, causal=False,
                      axis="sp"):
    """Static-graph op: uses the ambient mesh (set by
    CompiledProgram.run / mesh_guard)."""
    return ring_attention(q, k, v, axis=axis, scale=scale,
                          causal=causal)
