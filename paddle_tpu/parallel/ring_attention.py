"""Ring attention: sequence/context parallelism over the ``sp`` mesh
axis.

Not present in the 2019 reference (SURVEY §5 "long-context") — this is
a new TPU-first capability: sequences longer than one chip's HBM are
sharded over the mesh's ``sp`` axis; each device holds a query block
and the key/value blocks rotate around the ring with
``lax.ppermute`` (one ICI hop per step) while a numerically-stable
online softmax accumulates the attention output. Compute for block i
overlaps the transfer of block i+1 (XLA schedules the ppermute ahead),
so the ring cost hides behind the matmuls at transformer scale.

Composable three ways:
  - pure function ``ring_attention(q, k, v, ...)`` over globally
    sharded arrays (shard_map under the hood);
  - registered op ``ring_attention`` for static Programs (falls back
    to single-device fused attention when no sp axis is in scope);
  - inside user shard_map code via ``ring_attention_inner``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..ops.registry import register
from . import mesh as mesh_lib

_NEG = -1.0e30


def ring_attention_inner(q, k, v, *, axis_name, n_blocks, scale=1.0,
                         causal=False, bias_blk=None):
    """Per-shard body (call inside shard_map/pmap). q,k,v: local
    [B, H, S_loc, Dh] blocks of the sequence-sharded arrays."""
    B, H, Sq, Dh = q.shape
    Sk = k.shape[2]
    my = jax.lax.axis_index(axis_name)

    m = jnp.full((B, H, Sq, 1), _NEG, jnp.float32)
    l = jnp.zeros((B, H, Sq, 1), jnp.float32)
    acc = jnp.zeros((B, H, Sq, Dh), jnp.float32)
    perm = [(j, (j + 1) % n_blocks) for j in range(n_blocks)]

    q32 = q.astype(jnp.float32)
    for step in range(n_blocks):
        src = (my - step) % n_blocks  # whose k/v block we hold now
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k.astype(jnp.float32)) * scale
        if bias_blk is not None:
            s = s + bias_blk
        if causal:
            q_pos = my * Sq + jax.lax.broadcasted_iota(
                jnp.int32, (Sq, Sk), 0)
            k_pos = src * Sk + jax.lax.broadcasted_iota(
                jnp.int32, (Sq, Sk), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        m = m_new
        if step != n_blocks - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    out = acc / jnp.maximum(l, 1e-20)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis="sp", scale=1.0,
                   causal=False):
    """Global-view entry: q,k,v [B, H, S, Dh] (sharded or not — the
    shard_map in_specs place them on the sp axis)."""
    from jax.experimental.shard_map import shard_map

    from .ulysses import _full_attention

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # no sequence axis in scope: plain fused attention (shared
        # with the ulysses fallback so the numerics can't diverge)
        return _full_attention(q, k, v, scale, causal)

    n = mesh.shape[axis]
    spec = PartitionSpec(None, None, axis, None)
    f = shard_map(
        functools.partial(ring_attention_inner, axis_name=axis,
                          n_blocks=n, scale=scale, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return f(q, k, v)


@register("ring_attention", ["Q", "K", "V"], ["Out"])
def ring_attention_op(q, k, v, *, scale=1.0, causal=False,
                      axis="sp"):
    """Static-graph op: uses the ambient mesh (set by
    CompiledProgram.run / mesh_guard)."""
    return ring_attention(q, k, v, axis=axis, scale=scale,
                          causal=causal)
