"""Zigzag (load-balanced) ring attention for CAUSAL long-context.

The plain ring (ring_attention.py) is causally imbalanced: with
contiguous sequence shards, device 0 finds every rotated K/V block
masked while device n-1 attends them all — per-tick wall-clock is
gated by the busiest device, so the causal FLOP savings never
materialize. The zigzag layout fixes the balance:

  split S into 2n chunks; device d holds chunks (d, 2n-1-d).

Per ring hop against source s (holding chunks s, 2n-1-s), the four
chunk-pairs classify STATICALLY-BY-COMPARISON:

  (q_a=d,     k_a=s)      full if d>s, diagonal if d==s, empty if d<s
  (q_a=d,     k_b=2n-1-s) always empty   (d < n <= 2n-1-s)
  (q_b=2n-1-d, k_a=s)     always full    (2n-1-d >= n > s)
  (q_b=2n-1-d, k_b=2n-1-s) full if s>d, diagonal if s==d, empty if s<d

so EVERY device computes exactly two chunk-blocks per hop (one
always-full, one full-or-diagonal) — half the naive work, perfectly
balanced, with `lax.switch` on sign(d-s) selecting the live pair.
Chunks are contiguous in the ORIGINAL positions, so diagonal blocks
use the ordinary causal iota mask; the global entry permutes the
sequence in and inverse-permutes the output.

Online-softmax partials (m, l, acc per q-chunk) merge the sub-blocks
exactly as the plain ring does; gradients flow by autodiff through the
schedule (ppermute/switch/scan-free loop all have transposes).

No reference analog (SURVEY §5 long-context exceeds the 2019
reference); the layout is the zigzag/striped schedule of
llama3-style context parallelism, built on the same mesh machinery
as ring/Ulysses.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from . import mesh as mesh_lib

_NEG = -1.0e30


def _block_partial(q, k, v, scale, q_off, k_off, diagonal):
    """One chunk-pair's attention partials in f32: returns
    (pv [B,H,c,Dh], m [B,H,c,1], l [B,H,c,1]). diagonal=True applies
    the causal mask on absolute positions (chunks are contiguous
    spans, so iota + offsets suffice)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if diagonal:
        c, ck = q.shape[2], k.shape[2]
        q_pos = q_off + lax.broadcasted_iota(jnp.int32, (c, ck), 0)
        k_pos = k_off + lax.broadcasted_iota(jnp.int32, (c, ck), 1)
        s = jnp.where(k_pos <= q_pos, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p,
                    v.astype(jnp.float32))
    return pv, m, l


def online_merge(acc, m, l, pv, mb, lb):
    """Online-softmax merge of one block's partials into the running
    (acc, m, l) — the ring/zigzag-shared rescale (numerics notes: the
    _NEG sentinel makes the neutral element (0, _NEG, 0) exact, since
    exp(_NEG - m) underflows to 0 for any real m)."""
    m_new = jnp.maximum(m, mb)
    c0 = jnp.exp(m - m_new)
    c1 = jnp.exp(mb - m_new)
    return acc * c0 + pv * c1, m_new, l * c0 + lb * c1


def online_merge_nk(acc, m, l, pv, mb, lb):
    """No-keepdims variant of online_merge (stats [..., Sq] — the
    flash hop kernels' convention); the ONE copy both the flash ring
    and flash zigzag bodies should use."""
    m_new = jnp.maximum(m, mb)
    c0 = jnp.exp(m - m_new)
    c1 = jnp.exp(mb - m_new)
    return (acc * c0[..., None] + pv * c1[..., None], m_new,
            l * c0 + lb * c1)


def _neutral(pv, m, l):
    return jnp.zeros_like(pv), jnp.full_like(m, _NEG), jnp.zeros_like(l)


def zigzag_attention_inner(q, k, v, *, axis_name, n_blocks, scale=1.0):
    """Per-shard body. q,k,v local [B, H, 2c, Dh] in zigzag layout:
    rows [:c] are chunk d, rows [c:] are chunk 2n-1-d. Causal only
    (the balance problem this schedule solves is causal)."""
    n = n_blocks
    d = lax.axis_index(axis_name)
    c = q.shape[2] // 2
    qa, qb = q[:, :, :c], q[:, :, c:]
    perm = [(j, (j + 1) % n) for j in range(n)]

    def offs(chunk_idx):
        return chunk_idx * c

    B, H, _, Dh = q.shape
    zero = (jnp.zeros((B, H, c, Dh), jnp.float32),
            jnp.full((B, H, c, 1), _NEG, jnp.float32),
            jnp.zeros((B, H, c, 1), jnp.float32))
    state = [list(zero), list(zero)]

    for step in range(n):
        s_idx = (d - step) % n              # source device of k/v
        ka, kb = k[:, :, :c], k[:, :, c:]
        va, vb = v[:, :, :c], v[:, :, c:]
        qa_chunk, qb_chunk = d, 2 * n - 1 - d
        ka_chunk, kb_chunk = s_idx, 2 * n - 1 - s_idx

        # always-live pair: (q_b, k_a) — full, no mask
        pv, mb, lb = _block_partial(qb, ka, va, scale, None, None,
                                    diagonal=False)
        state[1] = list(online_merge(state[1][0], state[1][1],
                                     state[1][2], pv, mb, lb))

        # the comparison pair: exactly one of (qa,ka) / (qb,kb) is
        # live (full), or both are diagonal when d == s
        def qa_ka_full(_):
            pv, mb, lb = _block_partial(qa, ka, va, scale, None, None,
                                        diagonal=False)
            nb = _neutral(pv, mb, lb)
            return (pv, mb, lb) + nb

        def qb_kb_full(_):
            pv, mb, lb = _block_partial(qb, kb, vb, scale, None, None,
                                        diagonal=False)
            na = _neutral(pv, mb, lb)
            return na + (pv, mb, lb)

        def both_diag(_):
            pva, ma, la = _block_partial(
                qa, ka, va, scale, offs(qa_chunk), offs(ka_chunk),
                diagonal=True)
            pvb, mb_, lb_ = _block_partial(
                qb, kb, vb, scale, offs(qb_chunk), offs(kb_chunk),
                diagonal=True)
            return (pva, ma, la, pvb, mb_, lb_)

        # sign(d - s): -1 -> qb_kb full (s > d), 0 -> diagonals,
        # +1 -> qa_ka full (d > s)
        branch = jnp.sign(d - s_idx) + 1    # 0, 1, 2
        pva, ma, la, pvb, mb_, lb_ = lax.switch(
            branch, [qb_kb_full, both_diag, qa_ka_full], None)
        state[0] = list(online_merge(state[0][0], state[0][1],
                                     state[0][2], pva, ma, la))
        state[1] = list(online_merge(state[1][0], state[1][1],
                                     state[1][2], pvb, mb_, lb_))

        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    outs = []
    for acc, m, l in state:
        outs.append(acc / jnp.maximum(l, 1e-20))
    return jnp.concatenate(outs, axis=2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def zigzag_attention_inner_flash(q, k, v, axis_name, n_blocks, scale):
    """Flash zigzag body: each chunk-pair runs the pallas hop kernels
    (ops/pallas/ring.py) so scores stay in VMEM — the balanced
    schedule AND the flash memory profile together."""
    out, _ = _zz_flash_fwd(q, k, v, axis_name, n_blocks, scale)
    return out


def _zz_pair_neutral(B, H, c, Dh):
    return (jnp.zeros((B, H, c, Dh), jnp.float32),
            jnp.full((B, H, c), _NEG, jnp.float32),
            jnp.zeros((B, H, c), jnp.float32))


def _zz_flash_fwd(q, k, v, axis_name, n_blocks, scale):
    from ..ops.pallas import ring as R

    n = n_blocks
    d = lax.axis_index(axis_name)
    B, H, S2, Dh = q.shape
    c = S2 // 2
    qa, qb = q[:, :, :c], q[:, :, c:]
    k0, v0 = k, v
    perm = [(j, (j + 1) % n) for j in range(n)]

    # running per-chunk stats (m, l WITHOUT keepdims — fwd_block's
    # convention)
    acc_a = jnp.zeros((B, H, c, Dh), jnp.float32)
    m_a = jnp.full((B, H, c), _NEG, jnp.float32)
    l_a = jnp.zeros((B, H, c), jnp.float32)
    acc_b, m_b, l_b = acc_a, m_a, l_a

    merge = online_merge_nk

    for step in range(n):
        s_idx = (d - step) % n
        ka, kb = k[:, :, :c], k[:, :, c:]
        va, vb = v[:, :, :c], v[:, :, c:]
        off_qa, off_qb = d * c, (2 * n - 1 - d) * c
        off_ka, off_kb = s_idx * c, (2 * n - 1 - s_idx) * c

        # always-live full pair (q_b, k_a)
        pv, mb_, lb_ = R.fwd_block(qb, ka, va, off_qb, off_ka, scale,
                                   False)
        acc_b, m_b, l_b = merge(acc_b, m_b, l_b, pv, mb_, lb_)

        def qa_ka_full(_):
            pv, mm, ll = R.fwd_block(qa, ka, va, off_qa, off_ka,
                                     scale, False)
            return (pv, mm, ll) + _zz_pair_neutral(B, H, c, Dh)

        def qb_kb_full(_):
            pv, mm, ll = R.fwd_block(qb, kb, vb, off_qb, off_kb,
                                     scale, False)
            return _zz_pair_neutral(B, H, c, Dh) + (pv, mm, ll)

        def both_diag(_):
            pva, ma, la = R.fwd_block(qa, ka, va, off_qa, off_ka,
                                      scale, True)
            pvb, mb2, lb2 = R.fwd_block(qb, kb, vb, off_qb, off_kb,
                                        scale, True)
            return (pva, ma, la, pvb, mb2, lb2)

        branch = jnp.sign(d - s_idx) + 1
        pva, ma, la, pvb, mb2, lb2 = lax.switch(
            branch, [qb_kb_full, both_diag, qa_ka_full], None)
        acc_a, m_a, l_a = merge(acc_a, m_a, l_a, pva, ma, la)
        acc_b, m_b, l_b = merge(acc_b, m_b, l_b, pvb, mb2, lb2)

        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    l_a_s = jnp.maximum(l_a, 1e-20)
    l_b_s = jnp.maximum(l_b, 1e-20)
    out = jnp.concatenate(
        [acc_a / l_a_s[..., None], acc_b / l_b_s[..., None]],
        axis=2).astype(q.dtype)
    lse = jnp.concatenate([m_a + jnp.log(l_a_s),
                           m_b + jnp.log(l_b_s)], axis=2)
    return out, (q, k0, v0, out, lse)


def _zz_flash_bwd(axis_name, n_blocks, scale, res, g):
    from ..ops.pallas import ring as R

    q, k, v, out, lse = res
    n = n_blocks
    d = lax.axis_index(axis_name)
    B, H, S2, Dh = q.shape
    c = S2 // 2
    qa, qb = q[:, :, :c], q[:, :, c:]
    ga, gb = g[:, :, :c], g[:, :, c:]
    lse_a, lse_b = lse[:, :, :c], lse[:, :, c:]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    del_a, del_b = delta[:, :, :c], delta[:, :, c:]
    perm = [(j, (j + 1) % n) for j in range(n)]

    dqa = jnp.zeros((B, H, c, Dh), jnp.float32)
    dqb = jnp.zeros((B, H, c, Dh), jnp.float32)
    dk_acc = jnp.zeros_like(k, dtype=jnp.float32)
    dv_acc = jnp.zeros_like(v, dtype=jnp.float32)

    zero_q = jnp.zeros((B, H, c, Dh), jnp.float32)
    zero_k = jnp.zeros((B, H, c, Dh), jnp.float32)

    for step in range(n):
        s_idx = (d - step) % n
        ka, kb = k[:, :, :c], k[:, :, c:]
        va, vb = v[:, :, :c], v[:, :, c:]
        off_qa, off_qb = d * c, (2 * n - 1 - d) * c
        off_ka, off_kb = s_idx * c, (2 * n - 1 - s_idx) * c

        # always-live pair (q_b, k_a)
        dq_b1, dk_a1, dv_a1 = R.bwd_block(
            qb, ka, va, gb, lse_b, del_b, off_qb, off_ka, scale,
            False)

        def qa_ka_full(_):
            dq, dk, dv = R.bwd_block(qa, ka, va, ga, lse_a, del_a,
                                     off_qa, off_ka, scale, False)
            return (dq, zero_q, dk, zero_k, dv, zero_k)

        def qb_kb_full(_):
            dq, dk, dv = R.bwd_block(qb, kb, vb, gb, lse_b, del_b,
                                     off_qb, off_kb, scale, False)
            return (zero_q, dq, zero_k, dk, zero_k, dv)

        def both_diag(_):
            dqa_, dka_, dva_ = R.bwd_block(
                qa, ka, va, ga, lse_a, del_a, off_qa, off_ka, scale,
                True)
            dqb_, dkb_, dvb_ = R.bwd_block(
                qb, kb, vb, gb, lse_b, del_b, off_qb, off_kb, scale,
                True)
            return (dqa_, dqb_, dka_, dkb_, dva_, dvb_)

        branch = jnp.sign(d - s_idx) + 1
        dq_a2, dq_b2, dk_a2, dk_b2, dv_a2, dv_b2 = lax.switch(
            branch, [qb_kb_full, both_diag, qa_ka_full], None)

        dqa = dqa + dq_a2
        dqb = dqb + dq_b1 + dq_b2
        dk_hop = jnp.concatenate([dk_a1 + dk_a2, dk_b2], axis=2)
        dv_hop = jnp.concatenate([dv_a1 + dv_a2, dv_b2], axis=2)
        dk_acc = dk_acc + dk_hop
        dv_acc = dv_acc + dv_hop

        # k/v are not read after the last hop, but the accumulators
        # need every rotation to land home after n permutes
        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)

    dq = jnp.concatenate([dqa, dqb], axis=2)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


zigzag_attention_inner_flash.defvjp(_zz_flash_fwd, _zz_flash_bwd)


def _zigzag_perm(S, n):
    """Global position permutation: device-major concat of each
    device's (d, 2n-1-d) chunks. Returns (perm, inv) index arrays."""
    import numpy as np
    c = S // (2 * n)
    order = []
    for d in range(n):
        order.extend(range(d * c, (d + 1) * c))
        order.extend(range((2 * n - 1 - d) * c, (2 * n - d) * c))
    perm = np.asarray(order, np.int32)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(S, dtype=np.int32)
    return perm, inv


def zigzag_attention(q, k, v, mesh=None, axis="sp", scale=1.0,
                     use_flash=None):
    """Global-view causal attention in the zigzag schedule: q,k,v
    [B, H, S, Dh] in NATURAL sequence order; the permutation in/out is
    internal. S must divide by 2*sp. use_flash: None = auto (pallas
    chunk-pair kernels when the geometry fits and FLAGS.ring_flash is
    on); False forces the jnp body."""
    from jax.experimental.shard_map import shard_map

    from ..core.flags import FLAGS
    from ..ops.pallas import ring as R
    from .ulysses import _full_attention

    mesh = mesh or mesh_lib.current_mesh()
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        return _full_attention(q, k, v, scale, True)
    n = mesh.shape[axis]
    B, H, S, Dh = q.shape
    if S % (2 * n) != 0:
        raise ValueError("S=%d must divide by 2*sp=%d" % (S, 2 * n))
    c = S // (2 * n)
    if use_flash is None:
        use_flash = (FLAGS.ring_flash
                     and R.applicable(B, H, c, c, Dh,
                                      q.dtype.itemsize))
    perm, inv = _zigzag_perm(S, n)
    qz = jnp.take(q, perm, axis=2)
    kz = jnp.take(k, perm, axis=2)
    vz = jnp.take(v, perm, axis=2)
    spec = PartitionSpec(None, None, axis, None)

    if use_flash:
        def body(q_, k_, v_):
            return zigzag_attention_inner_flash(q_, k_, v_, axis, n,
                                                scale)
    else:
        def body(q_, k_, v_):
            return zigzag_attention_inner(q_, k_, v_, axis_name=axis,
                                          n_blocks=n, scale=scale)

    f = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=spec, check_rep=False)
    out = f(qz, kz, vz)
    return jnp.take(out, inv, axis=2)


from ..ops.registry import register  # noqa: E402


@register("zigzag_attention", ["Q", "K", "V"], ["Out"])
def zigzag_attention_op(q, k, v, *, scale=1.0, axis="sp"):
    """Static-graph op twin (the ring_attention_op pattern): uses the
    ambient mesh; without an sp axis it falls back to full causal
    attention."""
    return zigzag_attention(q, k, v, axis=axis, scale=scale)
