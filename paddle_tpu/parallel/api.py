"""User-facing sharding annotations.

The declarative replacement for the reference's multi-device graph
passes: instead of rewriting the op graph per device
(multi_devices_graph_pass.cc), users (or model libraries) annotate
variables with PartitionSpecs and the GSPMD partitioner does the rest.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from jax.sharding import PartitionSpec

from ..framework import Variable


def shard(var: Variable, *axes: Union[str, None, Sequence[str]]
          ) -> Variable:
    """Annotate a variable with a PartitionSpec, one entry per dim.

    Example (Megatron-style 2-way tensor parallel fc):
        w1 = shard(w1, None, "tp")   # column-parallel
        w2 = shard(w2, "tp", None)   # row-parallel
    """
    var.sharding = PartitionSpec(*axes)
    return var


def replicate(var: Variable) -> Variable:
    var.sharding = PartitionSpec()
    return var
