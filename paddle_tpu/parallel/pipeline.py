"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh
axis.

Not present in the 2019 reference (Fluid 1.4 predates its
PipelineTrainer) — a TPU-first capability completing the parallelism
matrix (dp x tp x sp x pp): layer stages are sharded over the ``pp``
axis, activations flow stage-to-stage with ``lax.ppermute`` (one ICI
hop per tick), and ``lax.scan`` drives the M + P - 1 tick schedule so
XLA sees ONE compiled loop, not unrolled Python. Autodiff works
through the whole schedule (scan/ppermute/dynamic-slice all have
transposes), so ``jax.grad`` of a pipelined loss yields exactly the
1F1B-equivalent backward without hand-written scheduling.

As of PR 19 the scheduler itself lives in ``engine.pipeline`` — the
schedule tables, the functional forward scan, the stage stacking, and
the microbatch validation are the SAME code the StepEngine traces when
a ``PipelinePlan`` rides a build strategy (gpipe AND 1F1B, forward and
backward, composed with guard/collectives/sharded-update inside the
one step trace). This module keeps the global-view ``gpipe_apply``
entry for user shard_map code: the explicit pp-mesh path (one stage
per device, ppermute transfers) plus the sequential reference
semantics when no pp axis is in scope.

The bubble fraction is (P-1)/(M+P-1) — callers pick n_micro >> pp for
efficiency; correctness holds for any M >= 1.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import PartitionSpec

# the scheduler plane is shared with the engine: these are the exact
# callables PipelinePlan traces inside build_step
from ..engine.pipeline import (gpipe_apply_inner, schedule_forward,
                               stack_stage_params,
                               validate_microbatches)
from . import mesh as mesh_lib

__all__ = ["gpipe_apply", "gpipe_apply_inner", "schedule_forward",
           "stack_stage_params", "validate_microbatches"]


def gpipe_apply(stage_fn, stacked_params, x, *, mesh=None, axis="pp",
                n_micro=None):
    """Global-view entry. stacked_params: pytree whose leaves have a
    leading stage axis [P, ...] (sharded over the pp mesh axis by the
    shard_map in_specs). x [B, ...]: the global batch; it is split
    into n_micro microbatches along axis 0 (B % n_micro == 0).
    Returns stage_fn applied through all P stages, [B, ...]."""
    from jax.experimental.shard_map import shard_map

    mesh = mesh or mesh_lib.current_mesh()
    n_params = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    B = x.shape[0]
    # validate BEFORE the mesh branch: the same call must behave
    # identically on one device and on a pod
    M = n_micro if n_micro is not None else n_params
    validate_microbatches(B, M)
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # no pipeline axis in scope: the engine's functional scheduler
        # over the SAME microbatches the meshed path uses — so a
        # stage_fn with cross-row coupling (batch statistics) cannot
        # silently diverge between one device and a pod
        xm = x.reshape((M, B // M) + x.shape[1:])
        return schedule_forward(stage_fn, stacked_params,
                                xm).reshape((B,) + x.shape[1:])

    P = mesh.shape[axis]
    if n_params != P:
        raise ValueError(
            "stacked_params has %d stages but the %r mesh axis has "
            "%d devices — one stage per device (a [k*P] stack would "
            "silently drop stages)" % (n_params, axis, P))
    x_micro = x.reshape((M, B // M) + x.shape[1:])

    # params: leading [P] axis sharded over pp; activations replicated
    # (each shard runs the full microbatch stream)
    p_spec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis), stacked_params)

    def body(params_shard, xm):
        params_local = jax.tree_util.tree_map(
            lambda a: a[0], params_shard)  # [1, ...] shard -> [...]
        out = gpipe_apply_inner(stage_fn, params_local, xm,
                                axis_name=axis, n_stages=P)
        # everyone returns their buffer; only the last stage's is
        # real. Rotate it to stage 0 so the out_specs slice (index 0
        # along a per-stage axis) carries the data.
        out = lax.ppermute(out, axis,
                           [(i, (i + 1) % P) for i in range(P)])
        return out[None]  # [1, M, b, ...] per stage

    f = shard_map(
        body, mesh=mesh,
        in_specs=(p_spec, PartitionSpec()),
        out_specs=PartitionSpec(axis),
        check_rep=False)
    out = f(stacked_params, x_micro)          # [P, M, b, ...]
    return out[0].reshape((B,) + x.shape[1:])
