"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh
axis.

Not present in the 2019 reference (Fluid 1.4 predates its
PipelineTrainer) — a TPU-first capability completing the parallelism
matrix (dp x tp x sp x pp): layer stages are sharded over the ``pp``
axis, activations flow stage-to-stage with ``lax.ppermute`` (one ICI
hop per tick), and ``lax.scan`` drives the M + P - 1 tick schedule so
XLA sees ONE compiled loop, not unrolled Python. Autodiff works
through the whole schedule (scan/ppermute/dynamic-slice all have
transposes), so ``jax.grad`` of a pipelined loss yields exactly the
1F1B-equivalent backward without hand-written scheduling.

Composable like the other parallel modules:
  - pure function ``gpipe_apply(stage_fn, stage_params, x, ...)`` over
    globally-sharded arrays (shard_map under the hood);
  - ``gpipe_apply_inner`` for use inside user shard_map code.

The bubble fraction is (P-1)/(M+P-1) — callers pick n_micro >> pp for
efficiency; correctness holds for any M >= 1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from . import mesh as mesh_lib


def gpipe_apply_inner(stage_fn, stage_params, x_micro, *, axis_name,
                      n_stages):
    """Per-shard GPipe body (call inside shard_map).

    stage_fn(params, x) -> y   — one stage's computation; the SAME
        callable runs on every stage with that stage's params shard.
        Input and output must have identical shape/dtype (the
        activation that travels the pipe).
    stage_params — this device's stage parameters (pytree).
    x_micro [M, ...] — the microbatches; every stage receives the same
        array, only stage 0 reads it.

    Returns y_micro [M, ...]: on the LAST stage, the pipeline outputs;
    on other stages, zeros (gpipe_apply ppermutes them home)."""
    stage = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    P = n_stages
    fwd_perm = [(i, (i + 1) % P) for i in range(P)]

    carry_act = jnp.zeros_like(x_micro[0])
    out_buf = jnp.zeros_like(x_micro)

    def tick(carry, t):
        act, outs = carry
        # stage 0 injects microbatch t (clamped; ticks >= M feed a
        # dummy that never reaches the output buffer)
        mb = lax.dynamic_index_in_dim(x_micro, jnp.minimum(t, M - 1),
                                      keepdims=False)
        inp = jnp.where(stage == 0, mb, act)
        y = stage_fn(stage_params, inp)
        # last stage completes microbatch t - (P-1) at tick t
        done_idx = t - (P - 1)
        outs = lax.cond(
            jnp.logical_and(stage == P - 1, done_idx >= 0),
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(done_idx, 0), 0),
            lambda o: o, outs)
        act_next = lax.ppermute(y, axis_name, fwd_perm)
        return (act_next, outs), None

    (_, out_buf), _ = lax.scan(tick, (carry_act, out_buf),
                               jnp.arange(M + P - 1))
    return out_buf


def gpipe_apply(stage_fn, stacked_params, x, *, mesh=None, axis="pp",
                n_micro=None):
    """Global-view entry. stacked_params: pytree whose leaves have a
    leading stage axis [P, ...] (sharded over the pp mesh axis by the
    shard_map in_specs). x [B, ...]: the global batch; it is split
    into n_micro microbatches along axis 0 (B % n_micro == 0).
    Returns stage_fn applied through all P stages, [B, ...]."""
    from jax.experimental.shard_map import shard_map

    mesh = mesh or mesh_lib.current_mesh()
    n_params = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    B = x.shape[0]
    # validate BEFORE the mesh branch: the same call must behave
    # identically on one device and on a pod
    M = n_micro if n_micro is not None else n_params
    if M < 1:
        raise ValueError("n_micro must be >= 1, got %r" % (n_micro,))
    if B % M != 0:
        raise ValueError("batch %d not divisible by n_micro %d"
                         % (B, M))
    if mesh is None or axis not in mesh.axis_names \
            or mesh.shape[axis] == 1:
        # no pipeline axis in scope: sequential reference semantics —
        # over the SAME microbatches the pipelined path uses, so a
        # stage_fn with cross-row coupling (batch statistics) cannot
        # silently diverge between one device and a pod
        xm = x.reshape((M, B // M) + x.shape[1:])
        outs = []
        for m in range(M):
            y = xm[m]
            for s in range(n_params):
                params_s = jax.tree_util.tree_map(lambda a: a[s],
                                                  stacked_params)
                y = stage_fn(params_s, y)
            outs.append(y)
        return jnp.concatenate(outs, axis=0)

    P = mesh.shape[axis]
    if n_params != P:
        raise ValueError(
            "stacked_params has %d stages but the %r mesh axis has "
            "%d devices — one stage per device (a [k*P] stack would "
            "silently drop stages)" % (n_params, axis, P))
    x_micro = x.reshape((M, B // M) + x.shape[1:])

    # params: leading [P] axis sharded over pp; activations replicated
    # (each shard runs the full microbatch stream)
    p_spec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis), stacked_params)

    def body(params_shard, xm):
        params_local = jax.tree_util.tree_map(
            lambda a: a[0], params_shard)  # [1, ...] shard -> [...]
        out = gpipe_apply_inner(stage_fn, params_local, xm,
                                axis_name=axis, n_stages=P)
        # everyone returns their buffer; only the last stage's is
        # real. Rotate it to stage 0 so the out_specs slice (index 0
        # along a per-stage axis) carries the data.
        out = lax.ppermute(out, axis,
                           [(i, (i + 1) % P) for i in range(P)])
        return out[None]  # [1, M, b, ...] per stage

    f = shard_map(
        body, mesh=mesh,
        in_specs=(p_spec, PartitionSpec()),
        out_specs=PartitionSpec(axis),
        check_rep=False)
    out = f(stacked_params, x_micro)          # [P, M, b, ...]
    return out[0].reshape((B,) + x.shape[1:])


def stack_stage_params(per_stage_params):
    """[{...}, {...}, ...] (one pytree per stage, equal structure) ->
    one pytree with leading [P] stage axis, ready for gpipe_apply."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)
