"""Explicit gradient-collective layer.

Until this module, every data-parallel gradient sync was an IMPLICIT
GSPMD all-reduce: the partitioner inserted a full-precision collective
wherever a batch-sharded gradient met a replicated parameter, and the
one part of the step dominating interconnect time could be neither
selected nor measured. This layer makes the sync first-class — three
selectable transports over the ``dp`` mesh axis, applied by the
executor as a rewrite of ``@GRAD`` values between the backward and
optimizer ops of the SAME traced step (XLA still fuses around them):

  - ``all_reduce_exact``       psum via shard_map — the explicit twin of
                               what GSPMD inserts implicitly.
  - ``reduce_scatter_gather``  the reduce-scatter + all-gather
                               decomposition of "Automatic Cross-Replica
                               Sharding of Weight Update"
                               (arXiv:2004.13336) — composes with the
                               ZeRO-style ``reduce_strategy=Reduce``
                               sharding ``compiler.py`` assigns, and is
                               bit-identical to the psum because both
                               reduce the same per-device partials in
                               rank order.
  - ``all_reduce_q8``          block-scaled int8 quantize →
                               reduce-scatter (all_to_all of int8 blocks
                               + f32 scales) → dequant/accumulate in
                               fp32 → requantize → all-gather, the
                               in-XLA quantized AllReduce of EQuARX
                               (arXiv:2506.17615), with a PERSISTENT
                               per-parameter error-feedback residual
                               (same lifecycle as the dgc U/V slots in
                               ``ops/optimizer_ops.py``) so compression
                               error is carried into the next step
                               instead of lost.

Formulation note: at trace level a gradient is one global value ``g``
(the full-batch gradient). The transports re-express the reduction over
per-device partials ``p_d = g/n`` — mathematically the identity for the
exact modes, but the collectives are REAL (psum / psum_scatter /
all_to_all / all_gather in the lowered HLO), so wire bytes, reduction
order, and quantization error are all faithfully modeled and
measurable. Known composition limit: on a real multi-device lowering
the partitioner may first materialize ``g`` replicated (its own
reduction) to satisfy shard_map's replicated in_specs, so the
END-TO-END wire bytes of a training step can exceed what the explicit
transport itself moves; the estimator below prices the transport
algorithms (what an HLO-native EQuARX-style pass moves), and the bench
rows report measured steps/s so the composition cost stays visible.
Consuming the pre-reduction partials (backward under shard_map) is the
follow-up that closes this gap. Error feedback follows the EF-SGD telescope: each device
compensates its contribution ``c = p + r`` before quantizing and carries
``r' = c - y/n`` forward, so ``sum_t y_t = sum_t g_t + n(r_0 - r_T)``
— the applied updates drift from the exact ones by a bounded amount
regardless of horizon.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec

from ..core.enforce import InvalidArgumentError, enforce

GRAD_SYNC_MODES = ("exact", "rs_ag", "q8")

# EQuARX-style block scaling: one f32 scale per 256 int8 elements keeps
# the scale overhead at 4/256 = 1.6% of payload.
DEFAULT_BLOCK_SIZE = 256

# Persistable error-feedback slot per parameter (created by
# ensure_residual_vars, threaded through the executor's persistable
# carry exactly like optimizer accumulators).
RESIDUAL_SUFFIX = ".q8_ef_residual"

_QMAX = 127.0


def residual_name(param_name: str) -> str:
    return param_name + RESIDUAL_SUFFIX


def axis_size(mesh, axis: str = "dp") -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))


def _numel(shape) -> int:
    return int(np.prod(shape)) if len(shape) else 1


def block_geometry(numel: int, world: int,
                   block_size: int = DEFAULT_BLOCK_SIZE
                   ) -> Tuple[int, int, int]:
    """(block, n_blocks, padded_len) for quantizing ``numel`` elements
    over ``world`` devices. Small tensors shrink the block (instead of
    padding a 64-element bias out to world*block elements) and n_blocks
    is rounded up to a multiple of ``world`` so the reduce-scatter deals
    whole blocks to every device."""
    world = max(1, int(world))
    bs = max(1, min(int(block_size), -(-numel // world)))
    nblk = -(-numel // bs)
    nblk = -(-nblk // world) * world
    return bs, nblk, nblk * bs


def quantize_q8(blocks):
    """Per-block symmetric int8: blocks [nblk, bs] f32 -> (q int8,
    scale f32 [nblk]). scale = blockmax/127 (1.0 for all-zero blocks so
    dequant is exactly 0); |dequant - x| <= scale/2 per element."""
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(blocks / scale[:, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def dequantize_q8(q, scale):
    return q.astype(jnp.float32) * scale[:, None]


def _pad_flat(x, padded_len: int):
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, padded_len - flat.shape[0]))


# ---------------------------------------------------------------------------
# the three transports
# ---------------------------------------------------------------------------

def all_reduce_exact(g, mesh, axis: str = "dp"):
    """Explicit psum of the per-device partials g/n via shard_map."""
    n = axis_size(mesh, axis)
    if n <= 1:
        return g

    def local(x):
        return lax.psum(x / n, axis)

    return shard_map(local, mesh=mesh, in_specs=PartitionSpec(),
                     out_specs=PartitionSpec(), check_rep=False)(g)


def reduce_scatter_gather(g, mesh, axis: str = "dp"):
    """arXiv:2004.13336 decomposition: psum_scatter the partials, then
    all_gather the reduced shards. Rank-order reduction makes it
    bit-identical to ``all_reduce_exact`` (fp32 reduce order fixed)."""
    n = axis_size(mesh, axis)
    if n <= 1:
        return g
    numel = _numel(g.shape)
    padded = -(-numel // n) * n

    def local(x):
        flat = _pad_flat(x / n, padded)
        shard = lax.psum_scatter(flat.reshape(n, padded // n), axis,
                                 scatter_dimension=0, tiled=False)
        full = lax.all_gather(shard, axis, axis=0, tiled=True)
        return full[:numel].reshape(x.shape)

    return shard_map(local, mesh=mesh, in_specs=PartitionSpec(),
                     out_specs=PartitionSpec(), check_rep=False)(g)


def all_reduce_q8(g, residual, mesh=None, axis: str = "dp",
                  block_size: int = DEFAULT_BLOCK_SIZE):
    """Block-quantized all-reduce with error feedback.

    Per device: compensate ``c = g/n + residual``; quantize c into
    int8 blocks + f32 scales; all_to_all so each device holds every
    peer's copy of ITS block range (the reduce-scatter — int8 on the
    wire); dequant and accumulate the n partial slices in fp32 in rank
    order; requantize the reduced slice; all_gather (int8 on the wire
    again); dequant. Returns ``(synced, new_residual)`` where
    ``new_residual = c - synced/n`` carries exactly what this step
    failed to transmit. On a 1-device mesh the transport disappears but
    the quantize/dequant round-trip and residual semantics remain, so
    the mode means the same thing at every scale."""
    n = axis_size(mesh, axis)
    out_dtype = jnp.asarray(g).dtype
    numel = _numel(np.shape(g))
    bs, nblk, padded = block_geometry(numel, n, block_size)

    def _qdq(c):
        q, s = quantize_q8(_pad_flat(c, padded).reshape(nblk, bs))
        return dequantize_q8(q, s).reshape(padded)[:numel] \
            .reshape(np.shape(c))

    if n <= 1:
        c = jnp.asarray(g).astype(jnp.float32) + residual
        y = _qdq(c)
        return y.astype(out_dtype), c - y

    def local(x, r):
        c = x.astype(jnp.float32) / n + r
        q, s = quantize_q8(_pad_flat(c, padded).reshape(nblk, bs))
        # reduce-scatter phase: device d ships block-range j of its
        # (q, s) to device j and receives every peer's range d
        q_t = lax.all_to_all(q.reshape(n, nblk // n, bs), axis,
                             split_axis=0, concat_axis=0, tiled=False)
        s_t = lax.all_to_all(s.reshape(n, nblk // n), axis,
                             split_axis=0, concat_axis=0, tiled=False)
        # dequant/accumulate in fp32, rank order (deterministic)
        part = q_t.astype(jnp.float32) * s_t[:, :, None]
        reduced = jnp.sum(part, axis=0)  # [nblk//n, bs]
        # all-gather phase: requantize the reduced shard so the gather
        # also moves int8 + scales, not fp32
        q2, s2 = quantize_q8(reduced)
        q2_all = lax.all_gather(q2, axis, axis=0, tiled=True)
        s2_all = lax.all_gather(s2, axis, axis=0, tiled=True)
        y = dequantize_q8(q2_all, s2_all).reshape(padded)[:numel] \
            .reshape(x.shape)
        return y.astype(out_dtype), c - y / n

    return shard_map(local, mesh=mesh,
                     in_specs=(PartitionSpec(), PartitionSpec()),
                     out_specs=(PartitionSpec(), PartitionSpec()),
                     check_rep=False)(g, residual)


# ---------------------------------------------------------------------------
# bytes-on-wire estimator
# ---------------------------------------------------------------------------

def bytes_on_wire(shape, mode: Optional[str], world: int,
                  block_size: int = DEFAULT_BLOCK_SIZE,
                  dtype_bytes: int = 4) -> int:
    """Estimated per-device wire bytes for the sync TRANSPORT of one
    gradient of ``shape`` over ``world`` devices, using the standard
    ring costs: all-reduce moves 2*(n-1)/n of the payload; the rs+ag
    decomposition moves the same total; q8 moves int8 blocks + f32
    scales through both phases. ``mode=None`` (implicit GSPMD) costs
    what the exact collective costs — the compiler inserts the same
    all-reduce. This prices the algorithm, not the full lowered step
    (see the module docstring's composition note)."""
    world = int(world)
    if world <= 1:
        return 0
    numel = _numel(tuple(shape))
    ring = 2.0 * (world - 1) / world
    if mode in (None, "", "exact", "rs_ag"):
        return int(round(ring * numel * dtype_bytes))
    if mode == "q8":
        bs, nblk, padded = block_geometry(numel, world, block_size)
        return int(round(ring * (padded + 4 * nblk)))
    raise InvalidArgumentError(
        "unknown gradient_sync mode %r (one of %s)"
        % (mode, (None,) + GRAD_SYNC_MODES))


def _sparse_grad_params(block) -> set:
    """Parameter names whose gradient arrives as SparseRows (produced
    by a lookup_table_grad op, nn_ops.py): the sync layer leaves those
    on the implicit path, so residual slots and byte estimates must
    not count them."""
    from ..framework import grad_var_name, Parameter
    sparse_grads = set()
    for op in block.ops:
        if op.type == "lookup_table_grad":
            sparse_grads.update(op.output_arg_names)
    return {p.name for p in block.vars.values()
            if isinstance(p, Parameter)
            and grad_var_name(p.name) in sparse_grads}


def grad_bytes_per_step(program, mode: Optional[str], world: int,
                        block_size: int = DEFAULT_BLOCK_SIZE) -> int:
    """Total estimated gradient-sync wire bytes for one train step of
    ``program`` (sum over its dense-synced trainable parameters)."""
    from ..framework import Parameter
    block = program.global_block()
    sparse = _sparse_grad_params(block)
    total = 0
    for p in block.vars.values():
        if isinstance(p, Parameter) and getattr(p, "trainable", True) \
                and p.name not in sparse:
            total += bytes_on_wire(p.shape, mode, world, block_size)
    return total


# ---------------------------------------------------------------------------
# executor integration: the @GRAD rewrite plan
# ---------------------------------------------------------------------------

class GradSyncPlan:
    """Where and how to rewrite gradient values inside one traced step:
    at op index ``boundary`` (the first optimize-role op that consumes
    a parameter gradient — i.e. after ALL backward accumulation, before
    regularizers/clipping/updates read the grads), replace each
    ``param@GRAD`` env entry with its synced value."""

    def __init__(self, mode, mesh, axis, boundary, entries, block_size):
        self.mode = mode
        self.mesh = mesh
        self.axis = axis
        self.boundary = boundary
        self.entries = entries  # [(param, grad_key, residual_key)]
        self.block_size = block_size

    def apply(self, env: Dict):
        from ..core.selected_rows import SparseRows
        for _pname, gkey, rkey in self.entries:
            v = env.get(gkey)
            if v is None or isinstance(v, SparseRows):
                # sparse embedding grads stay on the implicit path (the
                # same posture dgc takes: compressing an already-sparse
                # grad is redundant)
                continue
            if not jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating):
                continue
            if self.mode == "exact":
                env[gkey] = all_reduce_exact(v, self.mesh, self.axis)
            elif self.mode == "rs_ag":
                env[gkey] = reduce_scatter_gather(v, self.mesh,
                                                  self.axis)
            else:  # q8
                r = env.get(rkey)
                if r is None:
                    r = jnp.zeros(np.shape(v), jnp.float32)
                y, r_new = all_reduce_q8(v, r, self.mesh, self.axis,
                                         self.block_size)
                env[gkey] = y
                env[rkey] = r_new


def make_plan(block, mode: Optional[str], mesh, axis: str = "dp",
              block_size: int = DEFAULT_BLOCK_SIZE
              ) -> Optional[GradSyncPlan]:
    """Build the rewrite plan for a block, or None when the mode is
    unset or the block has no optimizer consuming parameter grads
    (inference/forward-only programs sync nothing)."""
    if not mode:
        return None
    enforce(mode in GRAD_SYNC_MODES,
            "BuildStrategy.gradient_sync must be one of %s, got %r",
            GRAD_SYNC_MODES, mode)
    from ..framework import Parameter, grad_var_name
    sparse = _sparse_grad_params(block)
    params = [p for p in block.vars.values()
              if isinstance(p, Parameter)
              and getattr(p, "trainable", True)
              and p.name not in sparse]
    if not params:
        return None
    grad_keys = {grad_var_name(p.name) for p in params}
    boundary = None
    for i, op in enumerate(block.ops):
        if op.attrs.get("op_role") == "optimize" and \
                any(n in grad_keys for n in op.input_arg_names):
            boundary = i
            break
    if boundary is None:
        return None
    entries = [(p.name, grad_var_name(p.name), residual_name(p.name))
               for p in sorted(params, key=lambda p: p.name)]
    return GradSyncPlan(mode, mesh, axis, boundary, entries, block_size)


def ensure_residual_vars(program, scope):
    """Create the persistable error-feedback residual var for every
    dense-synced trainable parameter (idempotent) and zero-fill it in
    ``scope`` so the executor's persistable carry picks it up from the
    first traced step — the same lifecycle as the dgc U/V accumulator
    slots. Memoized per (program version, scope) so the per-step
    dispatch path does not rescan the block."""
    from ..framework import Parameter
    memo = (program._version, id(scope))
    if getattr(program, "_q8_residual_memo", None) == memo:
        return
    block = program.global_block()
    sparse = _sparse_grad_params(block)
    for p in list(block.vars.values()):
        if not isinstance(p, Parameter) or \
                not getattr(p, "trainable", True) or p.name in sparse:
            continue
        rname = residual_name(p.name)
        if rname not in block.vars:
            block.create_var(name=rname, shape=tuple(p.shape),
                             dtype="float32", persistable=True,
                             stop_gradient=True)
        if not scope.has_var(rname) or scope.find_var(rname) is None:
            scope.set_var(rname,
                          jnp.zeros(tuple(p.shape), jnp.float32))
    program._q8_residual_memo = (program._version, id(scope))
