"""Explicit gradient-collective layer.

Until this module, every data-parallel gradient sync was an IMPLICIT
GSPMD all-reduce: the partitioner inserted a full-precision collective
wherever a batch-sharded gradient met a replicated parameter, and the
one part of the step dominating interconnect time could be neither
selected nor measured. This layer makes the sync first-class — three
selectable transports over the ``dp`` mesh axis, applied by the
executor as a rewrite of ``@GRAD`` values between the backward and
optimizer ops of the SAME traced step (XLA still fuses around them):

  - ``all_reduce_exact``       psum via shard_map — the explicit twin of
                               what GSPMD inserts implicitly.
  - ``reduce_scatter_gather``  the reduce-scatter + all-gather
                               decomposition of "Automatic Cross-Replica
                               Sharding of Weight Update"
                               (arXiv:2004.13336) — composes with the
                               ZeRO-style ``reduce_strategy=Reduce``
                               sharding ``compiler.py`` assigns, and is
                               bit-identical to the psum because both
                               reduce the same per-device partials in
                               rank order.
  - ``all_reduce_q8``          block-scaled int8 quantize →
                               reduce-scatter (all_to_all of int8 blocks
                               + f32 scales) → dequant/accumulate in
                               fp32 → requantize → all-gather, the
                               in-XLA quantized AllReduce of EQuARX
                               (arXiv:2506.17615), with a PERSISTENT
                               per-parameter error-feedback residual
                               (same lifecycle as the dgc U/V slots in
                               ``ops/optimizer_ops.py``) so compression
                               error is carried into the next step
                               instead of lost.

On top of the pointwise transports sit the ``sharded_update`` modes
(``ShardedUpdatePlan``): reduce-scatter the gradients and DON'T gather
them back — run the whole optimize section on 1/n flat shards over
1/n-sharded accumulator slots, then all-gather the fresh parameters
(optionally int8, with a second residual family and full-precision
master shards). See docs/gradient_sync.md §"Sharded weight update".

Formulation note: at trace level a gradient is one global value ``g``
(the full-batch gradient). The transports re-express the reduction over
per-device partials ``p_d = g/n`` — mathematically the identity for the
exact modes, but the collectives are REAL (psum / psum_scatter /
all_to_all / all_gather in the lowered HLO), so wire bytes, reduction
order, and quantization error are all faithfully modeled and
measurable. Known composition limit: on a real multi-device lowering
the partitioner may first materialize ``g`` replicated (its own
reduction) to satisfy shard_map's replicated in_specs, so the
END-TO-END wire bytes of a training step can exceed what the explicit
transport itself moves; the estimator below prices the transport
algorithms (what an HLO-native EQuARX-style pass moves), and the bench
rows report measured steps/s so the composition cost stays visible.
Consuming the pre-reduction partials (backward under shard_map) is the
follow-up that closes this gap. Error feedback follows the EF-SGD telescope: each device
compensates its contribution ``c = p + r`` before quantizing and carries
``r' = c - y/n`` forward, so ``sum_t y_t = sum_t g_t + n(r_0 - r_T)``
— the applied updates drift from the exact ones by a bounded amount
regardless of horizon.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec

from ..core.enforce import (InvalidArgumentError, UnimplementedError,
                            enforce)

# ZeRO-style sharded weight update (arXiv:2004.13336 proper): instead
# of all-gathering the reduced GRADIENT back to full size (rs_ag) so
# every replica applies the complete update over complete optimizer
# state, the ``sharded_update`` modes stop after the reduce-scatter,
# run regularizer/clip/optimizer ops on the 1/n gradient shard over
# 1/n-sharded accumulator slots, and all-gather the fresh PARAMETERS.
# ``sharded_update_q8`` rides the scatter leg on int8 blocks with the
# same per-param error-feedback residuals q8 uses; the gather leg can
# independently quantize (BuildStrategy.param_gather="q8", the EQuARX
# both-directions recipe, arXiv:2506.17615) with a SECOND persistable
# residual family on the param side plus a full-precision master shard
# so quantization error never compounds into the master weights.
SHARDED_MODES = ("sharded_update", "sharded_update_q8")
GRAD_SYNC_MODES = ("exact", "rs_ag", "q8") + SHARDED_MODES
PARAM_GATHER_MODES = ("fp32", "q8")

# EQuARX-style block scaling: one f32 scale per 256 int8 elements keeps
# the scale overhead at 4/256 = 1.6% of payload.
DEFAULT_BLOCK_SIZE = 256

# Persistable error-feedback slot per parameter (created by
# ensure_residual_vars, threaded through the executor's persistable
# carry exactly like optimizer accumulators).
RESIDUAL_SUFFIX = ".q8_ef_residual"

# Sharded-update state families (ensure_sharded_state): the param-side
# error-feedback residual of the quantized all-gather, and the
# full-precision master shard the update applies to when the gathered
# params are quantized approximations.
PARAM_RESIDUAL_SUFFIX = ".q8_pg_residual"
MASTER_SHARD_SUFFIX = ".zero_master_shard"

# Input slots whose vars must stay replicated scalars even when their
# shape happens to match the parameter's (scalar params): never
# converted into shard-shaped accumulator slots.
_NON_SLOT_INPUTS = ("LearningRate", "Beta1Pow", "Beta2Pow",
                    "ShouldApply", "CurrentStep")

_QMAX = 127.0


def residual_name(param_name: str) -> str:
    return param_name + RESIDUAL_SUFFIX


def param_residual_name(param_name: str) -> str:
    return param_name + PARAM_RESIDUAL_SUFFIX


def master_shard_name(param_name: str) -> str:
    return param_name + MASTER_SHARD_SUFFIX


def axis_size(mesh, axis: str = "dp") -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get(axis, 1))


def model_axes(mesh, sync_axis: str = "dp") -> Tuple[str, ...]:
    """The mesh's MODEL-parallel axes: every axis other than the
    gradient-sync axis with extent > 1 (sp/tp/ep/pp). These shard
    activations and expert weights inside the forward/backward; the
    gradient-sync layer operates along ``sync_axis`` only."""
    if mesh is None:
        return ()
    return tuple(a for a in mesh.axis_names
                 if a != sync_axis and mesh.shape[a] > 1)


def finish_model_partials(g, mesh, sync_axis: str = "dp"):
    """Pin a parameter gradient replicated over the mesh BEFORE it
    enters the dp gradient-sync bracket.

    Under a dp×sp (or ×tp/×ep) mesh the backward produces each weight
    gradient as PARTIAL sums distributed over the model axes (every sp
    shard contributes its sequence chunk's term). The dp transports'
    shard_map in_specs are replicated, so GSPMD must finish that
    partial reduction first — this constraint makes the seam explicit:
    the model-axis all-reduce lands HERE, once, immediately before the
    dp collective, instead of wherever the partitioner's propagation
    happens to put it (and the fusion-boundary audit sees one stable
    boundary). A no-op on pure-dp meshes."""
    if not model_axes(mesh, sync_axis):
        return g
    return jax.lax.with_sharding_constraint(
        g, NamedSharding(mesh, PartitionSpec()))


def _numel(shape) -> int:
    return int(np.prod(shape)) if len(shape) else 1


def block_geometry(numel: int, world: int,
                   block_size: int = DEFAULT_BLOCK_SIZE
                   ) -> Tuple[int, int, int]:
    """(block, n_blocks, padded_len) for quantizing ``numel`` elements
    over ``world`` devices. Small tensors shrink the block (instead of
    padding a 64-element bias out to world*block elements) and n_blocks
    is rounded up to a multiple of ``world`` so the reduce-scatter deals
    whole blocks to every device."""
    world = max(1, int(world))
    bs = max(1, min(int(block_size), -(-numel // world)))
    nblk = -(-numel // bs)
    nblk = -(-nblk // world) * world
    return bs, nblk, nblk * bs


def quantize_q8(blocks):
    """Per-block symmetric int8: blocks [nblk, bs] f32 -> (q int8,
    scale f32 [nblk]). scale = blockmax/127 (1.0 for all-zero blocks so
    dequant is exactly 0); |dequant - x| <= scale/2 per element."""
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(blocks / scale[:, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def dequantize_q8(q, scale):
    return q.astype(jnp.float32) * scale[:, None]


# ---------------------------------------------------------------------------
# host-side row codec (the sparse wire format)
# ---------------------------------------------------------------------------

# Embedding rows below this width ship exact fp32: at dim < 16 the
# 4-byte scale overhead erodes the int8 win (dim 8: 12/32 = 0.375x vs
# the 0.35x wire-bytes bar) and tiny rows are latency- not
# bandwidth-bound anyway.
SPARSE_Q8_MIN_DIM = 16


def quantize_rows_q8(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """NumPy twin of ``quantize_q8`` for the HOST sparse path
    (PUSH_SPARSE/PREFETCH payloads move through the RPC plane, never
    XLA): each embedding row is one quantization block — ``rows``
    [n, dim] f32 -> (q int8 [n, dim], scale f32 [n]). Same format and
    semantics as ``quantize_q8`` with ``block_size = dim`` (scale =
    rowmax/127, 1.0 for all-zero rows, |dequant - x| <= scale/2), so
    device- and wire-quantization error models match."""
    rows = np.ascontiguousarray(rows, np.float32)
    amax = np.max(np.abs(rows), axis=1)
    scale = np.where(amax > 0, amax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(rows / scale[:, None]), -_QMAX, _QMAX)
    return q.astype(np.int8), scale


def dequantize_rows_q8(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return q.astype(np.float32) * np.asarray(
        scale, np.float32)[:, None]


def sparse_wire_bytes(n_rows: int, dim: int, q8: bool,
                      ids_bytes: bool = True) -> int:
    """Payload bytes a sparse push/pull of ``n_rows`` moves: int64 ids
    (optional) + either f32 rows or int8 rows with one f32 scale each.
    Serialization headers excluded — this prices the algorithm, the
    bench rows report measured socket bytes."""
    ids = 8 * n_rows if ids_bytes else 0
    if q8:
        return ids + n_rows * (dim + 4)
    return ids + n_rows * dim * 4


def _pad_flat(x, padded_len: int):
    flat = x.reshape(-1)
    return jnp.pad(flat, (0, padded_len - flat.shape[0]))


# ---------------------------------------------------------------------------
# the three transports
# ---------------------------------------------------------------------------

def all_reduce_exact(g, mesh, axis: str = "dp"):
    """Explicit psum of the per-device partials g/n via shard_map."""
    n = axis_size(mesh, axis)
    if n <= 1:
        return g

    def local(x):
        return lax.psum(x / n, axis)

    return shard_map(local, mesh=mesh, in_specs=PartitionSpec(),
                     out_specs=PartitionSpec(), check_rep=False)(g)


def reduce_scatter_gather(g, mesh, axis: str = "dp"):
    """arXiv:2004.13336 decomposition: psum_scatter the partials, then
    all_gather the reduced shards. Rank-order reduction makes it
    bit-identical to ``all_reduce_exact`` (fp32 reduce order fixed)."""
    n = axis_size(mesh, axis)
    if n <= 1:
        return g
    numel = _numel(g.shape)
    padded = -(-numel // n) * n

    def local(x):
        flat = _pad_flat(x / n, padded)
        shard = lax.psum_scatter(flat.reshape(n, padded // n), axis,
                                 scatter_dimension=0, tiled=False)
        full = lax.all_gather(shard, axis, axis=0, tiled=True)
        return full[:numel].reshape(x.shape)

    return shard_map(local, mesh=mesh, in_specs=PartitionSpec(),
                     out_specs=PartitionSpec(), check_rep=False)(g)


def all_reduce_q8(g, residual, mesh=None, axis: str = "dp",
                  block_size: int = DEFAULT_BLOCK_SIZE):
    """Block-quantized all-reduce with error feedback.

    Per device: compensate ``c = g/n + residual``; quantize c into
    int8 blocks + f32 scales; all_to_all so each device holds every
    peer's copy of ITS block range (the reduce-scatter — int8 on the
    wire); dequant and accumulate the n partial slices in fp32 in rank
    order; requantize the reduced slice; all_gather (int8 on the wire
    again); dequant. Returns ``(synced, new_residual)`` where
    ``new_residual = c - synced/n`` carries exactly what this step
    failed to transmit. On a 1-device mesh the transport disappears but
    the quantize/dequant round-trip and residual semantics remain, so
    the mode means the same thing at every scale."""
    n = axis_size(mesh, axis)
    out_dtype = jnp.asarray(g).dtype
    numel = _numel(np.shape(g))
    bs, nblk, padded = block_geometry(numel, n, block_size)

    def _qdq(c):
        q, s = quantize_q8(_pad_flat(c, padded).reshape(nblk, bs))
        return dequantize_q8(q, s).reshape(padded)[:numel] \
            .reshape(np.shape(c))

    if n <= 1:
        c = jnp.asarray(g).astype(jnp.float32) + residual
        y = _qdq(c)
        return y.astype(out_dtype), c - y

    def local(x, r):
        c = x.astype(jnp.float32) / n + r
        q, s = quantize_q8(_pad_flat(c, padded).reshape(nblk, bs))
        # reduce-scatter phase: device d ships block-range j of its
        # (q, s) to device j and receives every peer's range d
        q_t = lax.all_to_all(q.reshape(n, nblk // n, bs), axis,
                             split_axis=0, concat_axis=0, tiled=False)
        s_t = lax.all_to_all(s.reshape(n, nblk // n), axis,
                             split_axis=0, concat_axis=0, tiled=False)
        # dequant/accumulate in fp32, rank order (deterministic)
        part = q_t.astype(jnp.float32) * s_t[:, :, None]
        reduced = jnp.sum(part, axis=0)  # [nblk//n, bs]
        # all-gather phase: requantize the reduced shard so the gather
        # also moves int8 + scales, not fp32
        q2, s2 = quantize_q8(reduced)
        q2_all = lax.all_gather(q2, axis, axis=0, tiled=True)
        s2_all = lax.all_gather(s2, axis, axis=0, tiled=True)
        y = dequantize_q8(q2_all, s2_all).reshape(padded)[:numel] \
            .reshape(x.shape)
        return y.astype(out_dtype), c - y / n

    return shard_map(local, mesh=mesh,
                     in_specs=(PartitionSpec(), PartitionSpec()),
                     out_specs=(PartitionSpec(), PartitionSpec()),
                     check_rep=False)(g, residual)


# ---------------------------------------------------------------------------
# sharded-update transports (arXiv:2004.13336): scatter grads, gather
# params. Each returns a GLOBAL flat [padded] array whose device layout
# is 1/n per replica over the dp axis — at trace level the global
# contents are the full padded tensor (so downstream global math, norms
# included, stays ordinary jax), while the per-chip footprint and the
# wire bytes are genuinely 1/n.
# ---------------------------------------------------------------------------

def reduce_scatter_shard(g, mesh, axis: str = "dp",
                         block_size: int = DEFAULT_BLOCK_SIZE):
    """Reduce-scatter the per-device partials ``g/n`` and STOP: returns
    the reduced gradient as a flat ``[padded]`` array sharded 1/n over
    ``axis`` (block_geometry padding so the same layout serves the q8
    variant and the shard-shaped accumulator slots). Rank-order
    psum_scatter — bit-identical content to ``all_reduce_exact``."""
    n = axis_size(mesh, axis)
    numel = _numel(np.shape(g))
    _bs, _nblk, padded = block_geometry(numel, n, block_size)
    if n <= 1:
        return _pad_flat(g, padded)

    def local(x):
        flat = _pad_flat(x / n, padded)
        return lax.psum_scatter(flat.reshape(n, padded // n), axis,
                                scatter_dimension=0, tiled=False)

    return shard_map(local, mesh=mesh, in_specs=PartitionSpec(),
                     out_specs=PartitionSpec(axis),
                     check_rep=False)(g)


def reduce_scatter_shard_q8(g, residual, mesh, axis: str = "dp",
                            block_size: int = DEFAULT_BLOCK_SIZE):
    """int8 reduce-scatter with error feedback: compensate
    ``c = g/n + r``, quantize into blocks, all_to_all the int8 blocks +
    f32 scales (each device receives every peer's copy of ITS block
    range), dequant/accumulate in fp32 rank order. Returns
    ``(grad_shard [padded] f32 sharded over axis, new_residual)`` where
    ``new_residual = c - qdq(c)`` is exactly what this device failed to
    ship — the same EF telescope as ``all_reduce_q8``, one quantization
    leg instead of two. On one device the wire disappears but the
    quantize/round-trip and residual semantics remain."""
    n = axis_size(mesh, axis)
    shape = np.shape(g)
    numel = _numel(shape)
    bs, nblk, padded = block_geometry(numel, n, block_size)

    if n <= 1:
        c = jnp.asarray(g).astype(jnp.float32) + residual
        q, s = quantize_q8(_pad_flat(c, padded).reshape(nblk, bs))
        sent = dequantize_q8(q, s).reshape(padded)
        return sent, c - sent[:numel].reshape(shape)

    def local(x, r):
        c = x.astype(jnp.float32) / n + r
        q, s = quantize_q8(_pad_flat(c, padded).reshape(nblk, bs))
        sent = dequantize_q8(q, s).reshape(padded)
        q_t = lax.all_to_all(q.reshape(n, nblk // n, bs), axis,
                             split_axis=0, concat_axis=0, tiled=False)
        s_t = lax.all_to_all(s.reshape(n, nblk // n), axis,
                             split_axis=0, concat_axis=0, tiled=False)
        reduced = jnp.sum(q_t.astype(jnp.float32) * s_t[:, :, None],
                          axis=0)  # [nblk//n, bs], rank order
        return reduced.reshape(-1), c - sent[:numel].reshape(x.shape)

    return shard_map(local, mesh=mesh,
                     in_specs=(PartitionSpec(), PartitionSpec()),
                     out_specs=(PartitionSpec(axis), PartitionSpec()),
                     check_rep=False)(g, residual)


def all_gather_params(p_shard, mesh, axis: str = "dp"):
    """fp32 all-gather of the freshly-updated param shards back to the
    full flat ``[padded]`` (replicated). Bit-exact: gather(slice(x))
    round-trips every element untouched."""
    n = axis_size(mesh, axis)
    if n <= 1:
        return p_shard

    def local(s):
        return lax.all_gather(s, axis, axis=0, tiled=True)

    return shard_map(local, mesh=mesh, in_specs=PartitionSpec(axis),
                     out_specs=PartitionSpec(),
                     check_rep=False)(p_shard)


def all_gather_params_q8(p_shard, residual, mesh, axis: str = "dp", *,
                         bs: int, nblk: int):
    """Quantized param gather with its OWN error feedback (EQuARX's
    second direction): compensate ``c = shard + r_p``, quantize the
    local block range, all-gather int8 + f32 scales, dequant. Returns
    ``(full_flat [padded] replicated, new_residual [padded] sharded)``
    with ``new_residual = c - qdq(c)``. The master shard (what the
    optimizer updates) never passes through the quantizer, so the error
    is bounded per step and the residual carries what each gather
    failed to express into the next one."""
    n = axis_size(mesh, axis)

    if n <= 1:
        c = p_shard + residual
        q, sc = quantize_q8(c.reshape(nblk, bs))
        y = dequantize_q8(q, sc).reshape(-1)
        return y, c - y

    def local(s, r):
        c = s + r
        q, sc = quantize_q8(c.reshape(nblk // n, bs))
        sent = dequantize_q8(q, sc).reshape(-1)
        q_all = lax.all_gather(q, axis, axis=0, tiled=True)
        sc_all = lax.all_gather(sc, axis, axis=0, tiled=True)
        return dequantize_q8(q_all, sc_all).reshape(-1), c - sent

    return shard_map(local, mesh=mesh,
                     in_specs=(PartitionSpec(axis), PartitionSpec(axis)),
                     out_specs=(PartitionSpec(), PartitionSpec(axis)),
                     check_rep=False)(p_shard, residual)


# ---------------------------------------------------------------------------
# bytes-on-wire estimator
# ---------------------------------------------------------------------------

def bytes_on_wire(shape, mode: Optional[str], world: int,
                  block_size: int = DEFAULT_BLOCK_SIZE,
                  dtype_bytes: int = 4,
                  param_gather: str = "fp32") -> int:
    """Estimated per-device wire bytes for the sync TRANSPORT of one
    gradient of ``shape`` over ``world`` devices, using the standard
    ring costs: all-reduce moves 2*(n-1)/n of the payload; the rs+ag
    decomposition moves the same total; q8 moves int8 blocks + f32
    scales through both phases. ``mode=None`` (implicit GSPMD) costs
    what the exact collective costs — the compiler inserts the same
    all-reduce. The sharded_update modes price their two HALF-trips
    separately: the reduce-scatter moves (n-1)/n of the (padded)
    payload ONCE (fp32, or int8 blocks + f32 scales under
    sharded_update_q8), and the param all-gather moves (n-1)/n once
    more, fp32 or int8+scales per ``param_gather``. This prices the
    algorithm, not the full lowered step (see the module docstring's
    composition note)."""
    world = int(world)
    if world <= 1:
        return 0
    numel = _numel(tuple(shape))
    ring = 2.0 * (world - 1) / world
    if mode in (None, "", "exact", "rs_ag"):
        return int(round(ring * numel * dtype_bytes))
    if mode == "q8":
        bs, nblk, padded = block_geometry(numel, world, block_size)
        return int(round(ring * (padded + 4 * nblk)))
    if mode in SHARDED_MODES:
        enforce(param_gather in PARAM_GATHER_MODES,
                "param_gather must be one of %s, got %r",
                PARAM_GATHER_MODES, param_gather)
        bs, nblk, padded = block_geometry(numel, world, block_size)
        half = (world - 1) / world
        q8_leg = half * (padded + 4 * nblk)
        fp_leg = half * padded * dtype_bytes
        scatter = q8_leg if mode == "sharded_update_q8" else fp_leg
        gather = q8_leg if param_gather == "q8" else fp_leg
        return int(round(scatter + gather))
    raise InvalidArgumentError(
        "unknown gradient_sync mode %r (one of %s)"
        % (mode, (None,) + GRAD_SYNC_MODES))


def _sparse_grad_params(block) -> set:
    """Parameter names whose gradient arrives as SparseRows (produced
    by a lookup_table_grad op, nn_ops.py): the sync layer leaves those
    on the implicit path, so residual slots and byte estimates must
    not count them."""
    from ..framework import grad_var_name, Parameter
    sparse_grads = set()
    for op in block.ops:
        if op.type == "lookup_table_grad":
            sparse_grads.update(op.output_arg_names)
    return {p.name for p in block.vars.values()
            if isinstance(p, Parameter)
            and grad_var_name(p.name) in sparse_grads}


def grad_bytes_per_step(program, mode: Optional[str], world: int,
                        block_size: int = DEFAULT_BLOCK_SIZE,
                        param_gather: str = "fp32") -> int:
    """Total estimated gradient-sync wire bytes for one train step of
    ``program`` (sum over its dense-synced trainable parameters)."""
    from ..framework import Parameter
    block = program.global_block()
    sparse = _sparse_grad_params(block)
    total = 0
    for p in block.vars.values():
        if isinstance(p, Parameter) and getattr(p, "trainable", True) \
                and p.name not in sparse:
            total += bytes_on_wire(p.shape, mode, world, block_size,
                                   param_gather=param_gather)
    return total


# ---------------------------------------------------------------------------
# executor integration: the @GRAD rewrite plan
# ---------------------------------------------------------------------------

class GradSyncPlan:
    """Where and how to rewrite gradient values inside one traced step:
    at op index ``boundary`` (the first optimize-role op that consumes
    a parameter gradient — i.e. after ALL backward accumulation, before
    regularizers/clipping/updates read the grads), replace each
    ``param@GRAD`` env entry with its synced value."""

    # pointwise rewrite plans have no closing hook; the executor probes
    # this uniformly (ShardedUpdatePlan sets a real index)
    end_boundary = None

    def __init__(self, mode, mesh, axis, boundary, entries, block_size):
        self.mode = mode
        self.mesh = mesh
        self.axis = axis
        self.boundary = boundary
        self.entries = entries  # [(param, grad_key, residual_key)]
        self.block_size = block_size

    def apply(self, env: Dict):
        from ..core.selected_rows import SparseRows
        for _pname, gkey, rkey in self.entries:
            v = env.get(gkey)
            if v is None or isinstance(v, SparseRows):
                # sparse embedding grads stay on the implicit path (the
                # same posture dgc takes: compressing an already-sparse
                # grad is redundant)
                continue
            if not jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating):
                continue
            # dp×sp/tp composition: the model-axis partial sums finish
            # here, so the transport below sees the SAME full-batch
            # gradient it sees on a pure-dp mesh (and q8's residual
            # telescope stays a dp-axis-only story)
            v = finish_model_partials(v, self.mesh, self.axis)
            if self.mode == "exact":
                env[gkey] = all_reduce_exact(v, self.mesh, self.axis)
            elif self.mode == "rs_ag":
                env[gkey] = reduce_scatter_gather(v, self.mesh,
                                                  self.axis)
            else:  # q8
                r = env.get(rkey)
                if r is None:
                    r = jnp.zeros(np.shape(v), jnp.float32)
                y, r_new = all_reduce_q8(v, r, self.mesh, self.axis,
                                         self.block_size)
                env[gkey] = y
                env[rkey] = r_new


class _ShardEntry:
    """Per-parameter record of the sharded bracket: geometry, the
    shard-shaped accumulator slots, and the names of the sharded-state
    families (grad residual / param residual / master shard)."""

    __slots__ = ("pname", "gkey", "shape", "numel", "bs", "nblk",
                 "padded", "slots", "grad_res_key", "param_res_key",
                 "master_key")

    def __init__(self, pname, shape, bs, nblk, padded, slots):
        from ..framework import grad_var_name
        self.pname = pname
        self.gkey = grad_var_name(pname)
        self.shape = tuple(shape)
        self.numel = _numel(self.shape)
        self.bs, self.nblk, self.padded = bs, nblk, padded
        self.slots = list(slots)
        self.grad_res_key = residual_name(pname)
        self.param_res_key = param_residual_name(pname)
        self.master_key = master_shard_name(pname)


def sharded_entries(block, world: int,
                    block_size: int = DEFAULT_BLOCK_SIZE,
                    reject_dgc: bool = True):
    """(boundary, end_boundary, entries) of the shard→update→gather
    bracket for a block. ``boundary`` is the first non-vjp op consuming
    a dense trainable parameter gradient (regularizers carry backward
    role in this codebase, so the pointwise plans' optimize-role rule
    would open the bracket too late); ``end_boundary`` is one past the
    last op that writes a bracketed parameter. Slot vars are the
    persistable param-shaped inputs/outputs of the update ops (adam
    m/v, momentum velocities, grad-accumulation Acc, AMP master copies
    — anything shaped like the param that the update carries), found by
    scanning ops that either write the param or consume its gradient;
    LR/beta-pow/counter scalars are excluded by slot name."""
    from ..framework import Parameter, grad_var_name
    sparse = _sparse_grad_params(block)
    params = {p.name: p for p in block.vars.values()
              if isinstance(p, Parameter)
              and getattr(p, "trainable", True)
              and p.name not in sparse}
    if not params:
        return None, None, []
    g2p = {grad_var_name(n): n for n in params}
    boundary = None
    end = None
    slot_map = {n: [] for n in params}
    wrote_param = set()
    for i, op in enumerate(block.ops):
        if op.type in ("vjp", "vjp2"):
            continue
        ins = op.input_arg_names
        outs = op.output_arg_names
        consumed = [g2p[n] for n in ins if n in g2p]
        wrote = [n for n in outs if n in params]
        if boundary is None and consumed:
            boundary = i
        if op.attrs.get("op_role") != "optimize":
            continue
        if op.type == "dgc" and reject_dgc:
            # only the sharded transports reject dgc; measurement
            # callers (slot_bytes_per_chip) scan any program
            raise UnimplementedError(
                "sharded_update does not compose with dgc: its top-k "
                "threshold needs the full |v| tensor on every replica; "
                "use gradient_sync='q8' with DGCMomentumOptimizer")
        owner = wrote[0] if wrote else (consumed[0] if consumed else
                                        None)
        if owner is None:
            continue
        if wrote:
            end = i + 1
            wrote_param.update(wrote)
        pshape = tuple(params[owner].shape)
        pnumel = _numel(pshape)
        skip = {owner}
        for slot_name in _NON_SLOT_INPUTS:
            skip.update(op.inputs.get(slot_name, ()))
        for n in list(ins) + list(outs):
            if n in skip or n in g2p:
                continue
            v = block.vars.get(n)
            if v is None or not v.persistable \
                    or isinstance(v, Parameter):
                continue
            geom = getattr(v, "_shard_geometry", None)
            if tuple(v.shape) == pshape or \
                    (geom is not None and geom[0] == pnumel):
                if n not in slot_map[owner]:
                    slot_map[owner].append(n)
    if boundary is None or end is None:
        return None, None, []
    entries = []
    for pname in sorted(wrote_param):
        p = params[pname]
        numel = _numel(tuple(p.shape))
        bs, nblk, padded = block_geometry(numel, world, block_size)
        entries.append(_ShardEntry(pname, p.shape, bs, nblk, padded,
                                   slot_map[pname]))
    return boundary, end, entries


class ShardedUpdatePlan:
    """The shard→update→gather bracket around the optimize-role ops.

    ``apply`` (at ``boundary``): reduce-scatter each dense parameter
    gradient to a flat ``[padded]`` shard (fp32 bit-exact, or int8
    blocks with grad-side error feedback under sharded_update_q8) and
    swap the param env entry to its flat shard — the master shard when
    the param gather quantizes, a free local slice of the full param
    otherwise. Every op inside the bracket (regularizer, clip,
    accumulation, update — including the batched multi_tensor_adam
    path) then runs on 1/n-laid-out flats; global reductions (norm
    clip, lamb trust ratios) still see the full global value, with
    GSPMD reducing the sharded operand.

    ``finish`` (at ``end_boundary``): carry the updated shard into the
    master slot, all-gather the fresh params (fp32, or int8 + scales
    with the param-side residual), and restore the param env entry to
    full shape for everything downstream (EMA/averaging ops, the next
    step's forward). When the anomaly guard's flag is in the env, a
    gated (bad) step select-restores the gathered params and the
    param-side residuals, so a skipped step leaves shards, residuals,
    and params bit-identical."""

    def __init__(self, mode, param_gather, mesh, axis, boundary,
                 end_boundary, entries, block_size):
        self.mode = mode
        self.quant_grads = mode == "sharded_update_q8"
        self.param_gather = param_gather
        self.mesh = mesh
        self.axis = axis
        self.boundary = boundary
        self.end_boundary = end_boundary
        self.entries = entries
        self.block_size = block_size

    def _shard_layout(self, flat):
        if axis_size(self.mesh, self.axis) > 1:
            return jax.lax.with_sharding_constraint(
                flat, NamedSharding(self.mesh,
                                    PartitionSpec(self.axis)))
        return flat

    def apply(self, env: Dict):
        from ..core.selected_rows import SparseRows
        for e in self.entries:
            g = env.get(e.gkey)
            p_full = env.get(e.pname)
            if g is None or p_full is None \
                    or isinstance(g, SparseRows):
                continue
            if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
                continue
            # model-axis partial sums must complete before the shard
            # bracket opens (see GradSyncPlan.apply)
            g = finish_model_partials(g, self.mesh, self.axis)
            if self.quant_grads:
                r = env.get(e.grad_res_key)
                if r is None:
                    r = jnp.zeros(e.shape, jnp.float32)
                gs, r_new = reduce_scatter_shard_q8(
                    g, r, self.mesh, self.axis, self.block_size)
                env[e.grad_res_key] = r_new
            else:
                gs = reduce_scatter_shard(g, self.mesh, self.axis,
                                          self.block_size)
            env[e.gkey] = gs
            env[("sharded_full", e.pname)] = p_full
            master = env.get(e.master_key) \
                if self.param_gather == "q8" else None
            if master is not None:
                env[e.pname] = master
            else:
                env[e.pname] = self._shard_layout(
                    _pad_flat(p_full, e.padded))

    def finish(self, env: Dict):
        from ..resilience.guard import FLAG_KEY
        flag = env.get(FLAG_KEY)
        for e in self.entries:
            key = ("sharded_full", e.pname)
            if key not in env:
                continue
            old_full = env.pop(key)
            shard = env[e.pname]
            if self.param_gather == "q8":
                # the exact master carries forward; gate protection is
                # inherited from the update op's own select
                env[e.master_key] = shard
                rp = env.get(e.param_res_key)
                if rp is None:
                    rp = self._shard_layout(
                        jnp.zeros((e.padded,), jnp.float32))
                full_flat, rp_new = all_gather_params_q8(
                    shard, rp, self.mesh, self.axis,
                    bs=e.bs, nblk=e.nblk)
                if flag is not None:
                    rp_new = jnp.where(flag, rp_new, rp)
                env[e.param_res_key] = rp_new
            else:
                full_flat = all_gather_params(shard, self.mesh,
                                              self.axis)
            full = full_flat[:e.numel].reshape(e.shape).astype(
                jnp.asarray(old_full).dtype)
            if flag is not None:
                full = jnp.where(flag, full, old_full)
            env[e.pname] = full
            # the full gradient ceases to exist after the scatter
            # (that IS the ZeRO memory win) — drop the flat shard so a
            # downstream read/fetch fails loudly instead of silently
            # seeing a [padded] 1/n slice where every other mode
            # yields the full synced gradient
            env.pop(e.gkey, None)


def make_plan(block, mode: Optional[str], mesh, axis: str = "dp",
              block_size: int = DEFAULT_BLOCK_SIZE,
              param_gather: str = "fp32"):
    """Build the rewrite plan for a block, or None when the mode is
    unset or the block has no optimizer consuming parameter grads
    (inference/forward-only programs sync nothing)."""
    if not mode:
        return None
    enforce(mode in GRAD_SYNC_MODES,
            "BuildStrategy.gradient_sync must be one of %s, got %r",
            GRAD_SYNC_MODES, mode)
    if mode in SHARDED_MODES:
        enforce(mesh is not None,
                "sharded_update needs a device mesh (run through "
                "CompiledProgram.with_data_parallel)")
        enforce(param_gather in PARAM_GATHER_MODES,
                "BuildStrategy.param_gather must be one of %s, got %r",
                PARAM_GATHER_MODES, param_gather)
        world = axis_size(mesh, axis)
        boundary, end, entries = sharded_entries(block, world,
                                                 block_size)
        if boundary is None or not entries:
            return None
        return ShardedUpdatePlan(mode, param_gather, mesh, axis,
                                 boundary, end, entries, block_size)
    from ..framework import Parameter, grad_var_name
    sparse = _sparse_grad_params(block)
    params = [p for p in block.vars.values()
              if isinstance(p, Parameter)
              and getattr(p, "trainable", True)
              and p.name not in sparse]
    if not params:
        return None
    grad_keys = {grad_var_name(p.name) for p in params}
    boundary = None
    for i, op in enumerate(block.ops):
        if op.attrs.get("op_role") == "optimize" and \
                any(n in grad_keys for n in op.input_arg_names):
            boundary = i
            break
    if boundary is None:
        return None
    entries = [(p.name, grad_var_name(p.name), residual_name(p.name))
               for p in sorted(params, key=lambda p: p.name)]
    return GradSyncPlan(mode, mesh, axis, boundary, entries, block_size)


def _scope_uid(scope) -> int:
    """Monotonic scope identity for memo keys. NEVER id(scope): a GC'd
    scope's address is reused by fresh scopes, and a recycled id with a
    matching program version silently skips state creation for the new
    scope (the residual-memo bug this replaced)."""
    return getattr(scope, "_uid", None) or id(scope)


def ensure_residual_vars(program, scope):
    """Create the persistable error-feedback residual var for every
    dense-synced trainable parameter (idempotent) and zero-fill it in
    ``scope`` so the executor's persistable carry picks it up from the
    first traced step — the same lifecycle as the dgc U/V accumulator
    slots. Memoized per (program version, scope uid) so the per-step
    dispatch path does not rescan the block."""
    from ..framework import Parameter
    memo = (program._version, _scope_uid(scope))
    if getattr(program, "_q8_residual_memo", None) == memo:
        return
    block = program.global_block()
    sparse = _sparse_grad_params(block)
    for p in list(block.vars.values()):
        if not isinstance(p, Parameter) or \
                not getattr(p, "trainable", True) or p.name in sparse:
            continue
        rname = residual_name(p.name)
        if rname not in block.vars:
            block.create_var(name=rname, shape=tuple(p.shape),
                             dtype="float32", persistable=True,
                             stop_gradient=True)
        if not scope.has_var(rname) or scope.find_var(rname) is None:
            scope.set_var(rname,
                          jnp.zeros(tuple(p.shape), jnp.float32))
    program._q8_residual_memo = (program._version, _scope_uid(scope))


# ---------------------------------------------------------------------------
# sharded-update state lifecycle
# ---------------------------------------------------------------------------

def _place_shard(arr: np.ndarray, mesh, axis: str):
    """Device-place a flat [padded] host array 1/n over the axis (or
    just on-device for a 1-wide axis)."""
    if mesh is not None and axis_size(mesh, axis) > 1:
        return jax.device_put(
            arr, NamedSharding(mesh, PartitionSpec(axis)))
    return jnp.asarray(arr)


def _to_padded_flat(value, padded: int) -> np.ndarray:
    arr = np.asarray(jax.device_get(value))
    out = np.zeros((padded,), arr.dtype)
    out[:arr.size] = arr.reshape(-1)
    return out


def ensure_sharded_state(program, scope, mesh, axis: str = "dp",
                         param_gather: str = "fp32",
                         block_size: int = DEFAULT_BLOCK_SIZE):
    """Convert ``program``'s optimizer accumulator slots to the sharded
    layout and make sure ``scope`` carries them (plus, under
    ``param_gather='q8'``, the master shards seeded from the current
    params and the zeroed param-side residuals).

    Idempotent and value-preserving: a full-shape slot value already in
    the scope (startup-program zeros, or a replicated-era training
    state) is pad-flattened into the ``[padded]`` shard layout; an
    already-converted value is left alone. Block declarations are
    reshaped to ``(padded,)``, annotated with ``sharding=P(axis)`` (so
    the executor's persist placement and jit out_shardings pin the 1/n
    layout) and stamped with ``_shard_geometry=(numel, padded)`` (so
    checkpoint restore recognizes the layout — io._check_and_set).
    Memoized per (program version, scope uid, world, param_gather) so
    the per-step dispatch path does not rescan the block. Run the
    startup program BEFORE the first sharded step; re-running it
    afterwards resets the slots to full-shape zeros behind the memo's
    back (the same lifecycle contract as the q8 residuals)."""
    enforce(param_gather in PARAM_GATHER_MODES,
            "param_gather must be one of %s, got %r",
            PARAM_GATHER_MODES, param_gather)
    world = axis_size(mesh, axis)
    memo = (program._version, _scope_uid(scope), world, param_gather,
            block_size)
    if getattr(program, "_sharded_state_memo", None) == memo:
        return
    block = program.global_block()
    boundary, _end, entries = sharded_entries(block, world, block_size)
    if boundary is None or not entries:
        program._sharded_state_memo = memo
        return
    changed = False
    for e in entries:
        geom = (e.numel, e.padded)
        names = list(e.slots)
        if param_gather == "q8":
            for extra in (e.master_key, e.param_res_key):
                if extra not in block.vars:
                    block.create_var(name=extra, shape=(e.padded,),
                                     dtype="float32", persistable=True,
                                     stop_gradient=True)
                    changed = True
            names += [e.master_key, e.param_res_key]
        for name in names:
            v = block.vars[name]
            if tuple(v.shape) != (e.padded,):
                v.shape = (e.padded,)
                changed = True
            if getattr(v, "_shard_geometry", None) != geom:
                v._shard_geometry = geom
                v.sharding = PartitionSpec(axis)
                changed = True
        for name in e.slots:
            if not scope.has_var(name):
                continue
            val = scope.find_var(name)
            if val is None or tuple(np.shape(val)) == (e.padded,):
                continue
            vnumel = int(np.prod(np.shape(val))) if np.shape(val) \
                else 1
            # a full-shape value (startup zeros / replicated-era
            # training state) has the param's numel; anything else flat
            # is a shard padded for a DIFFERENT world size — padding it
            # again would corrupt or crash deep in numpy, so be loud
            enforce(vnumel == e.numel,
                    "optimizer slot %r holds a [%d] shard but this "
                    "mesh's layout wants [%d] (param numel %d): the "
                    "scope was converted under a different device "
                    "count — sharded_update state must be restored and "
                    "run under the same device count it was trained "
                    "with", name, vnumel, e.padded, e.numel)
            scope.set_var(name, _place_shard(
                _to_padded_flat(val, e.padded), mesh, axis))
        if param_gather == "q8":
            # the master/residual families only ever exist in the
            # [padded] layout (created here or checkpoint-restored), so
            # a present-but-wrong-shape value is sharded state from a
            # DIFFERENT device count — reseeding the master from the
            # current param would bake the quantized gather image into
            # the exact masters and zeroing the residual would drop the
            # EF history, so be as loud as the slot conversion above
            for fam in (e.master_key, e.param_res_key):
                fval = scope.find_var(fam) if scope.has_var(fam) \
                    else None
                if fval is not None \
                        and tuple(np.shape(fval)) != (e.padded,):
                    fnumel = int(np.prod(np.shape(fval))) \
                        if np.shape(fval) else 1
                    enforce(False,
                            "sharded state %r holds a [%d] shard but "
                            "this mesh's layout wants [%d]: the scope "
                            "was converted under a different device "
                            "count — sharded_update state must be "
                            "restored and run under the same device "
                            "count it was trained with",
                            fam, fnumel, e.padded)
            pval = scope.find_var(e.pname) \
                if scope.has_var(e.pname) else None
            mval = scope.find_var(e.master_key) \
                if scope.has_var(e.master_key) else None
            if mval is None and pval is not None:
                # seed the master from the CURRENT full param — the
                # full var becomes the quantized gather's output from
                # the next step on, the master stays exact
                scope.set_var(e.master_key, _place_shard(
                    _to_padded_flat(pval, e.padded).astype(np.float32),
                    mesh, axis))
            rval = scope.find_var(e.param_res_key) \
                if scope.has_var(e.param_res_key) else None
            if rval is None:
                scope.set_var(e.param_res_key, _place_shard(
                    np.zeros((e.padded,), np.float32), mesh, axis))
    if changed:
        program._bump()
    program._sharded_state_memo = (program._version, _scope_uid(scope),
                                   world, param_gather, block_size)


def reject_stale_sharded_layout(block):
    """Refuse to trace update ops over shard-laid-out slots without a
    ShardedUpdatePlan.

    ``ensure_sharded_state`` rewrites a program's accumulator slot
    DECLARATIONS to the flat ``[padded]`` layout; that program's
    optimize-role ops only make sense inside the shard→update→gather
    bracket. Running it through a non-sharded path (plain ``exe.run``,
    a CompiledProgram without a sharded ``gradient_sync``,
    ``run_repeated``/``run_pipelined`` on the raw program) would crash
    deep in the update lowering with a bare shape mismatch — or worse,
    broadcast a ``[padded]`` slot against a full-shape grad. Detect it
    at trace time and say what happened. A ``clone(for_test=True)``
    program passes: its optimizer ops are pruned, and forward ops never
    touch slot vars."""
    for op in block.ops:
        if op.attrs.get("op_role") != "optimize":
            continue
        for n in list(op.input_arg_names) + list(op.output_arg_names):
            v = block.vars.get(n)
            if v is not None and \
                    getattr(v, "_shard_geometry", None) is not None:
                raise InvalidArgumentError(
                    "op %r reads optimizer slot %r which is in the "
                    "1/n sharded layout (converted by "
                    "gradient_sync='sharded_update'): this program "
                    "must keep running through the sharded "
                    "CompiledProgram that converted it — a plain run "
                    "would corrupt the shards" % (op.type, n))


def slot_bytes_per_chip(program, scope) -> int:
    """Measured per-chip bytes of the optimizer's per-parameter carry:
    accumulator slots plus (when present) master shards and param-side
    residuals, summed over the scope's live values. A value with a
    sharding contributes its per-device shard size (replicated values
    count in full — every chip holds them); host arrays count in full.
    This is the number the sharded_update memory claim is about: under
    a dp=n mesh it scales ~1/n of the replicated total."""
    block = program.global_block()
    _b, _e, entries = sharded_entries(block, 1, reject_dgc=False)
    total = 0
    seen = set()
    for e in entries:
        names = list(e.slots)
        for extra in (e.master_key, e.param_res_key):
            if extra in block.vars:
                names.append(extra)
        for name in names:
            if name in seen:
                continue
            seen.add(name)
            val = scope.find_var(name) if scope.has_var(name) else None
            if val is None:
                continue
            sh = getattr(val, "sharding", None)
            if sh is not None and hasattr(sh, "shard_shape"):
                shard = sh.shard_shape(tuple(val.shape))
                total += int(np.prod(shard)) * val.dtype.itemsize
            else:
                total += int(np.asarray(val).nbytes)
    return total
