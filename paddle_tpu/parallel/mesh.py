"""Device mesh management.

Reference: the reference manages device groups through NCCL communicator
maps — platform/nccl_helper.h:90 ``NCCLContextMap`` (one comm per
device) and :179 ``MultiNCCLContextMap`` (flat + hierarchical inter/
intra-node comm sets), bootstrapped by gen_nccl_id_op.cc.

TPU-native redesign: a named ``jax.sharding.Mesh`` replaces communicator
maps entirely. Axis names declare *roles* (dp/tp/pp/sp/ep); collectives
are inserted by the XLA GSPMD partitioner from sharding annotations and
ride ICI within a slice and DCN across slices. The hierarchical-allreduce
configuration of the reference corresponds to a 2-D ("dcn", "ici") mesh
layout where jax places the slower axis over DCN automatically.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.enforce import InvalidArgumentError, enforce

# Canonical axis names, in nesting order (outermost first). dp=data,
# pp=pipeline, tp=tensor/model, sp=sequence/context, ep=expert.
AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None
              ) -> Mesh:
    """Create a Mesh with named axes, e.g. make_mesh({"dp": 4, "tp": 2}).

    Axis sizes must multiply to the device count. ``tp`` (and ``sp``)
    are placed innermost so they map to the fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = [a for a in AXIS_ORDER if a in axes]
    extra = [a for a in axes if a not in AXIS_ORDER]
    names += extra
    sizes = [axes[a] for a in names]
    total = int(np.prod(sizes)) if sizes else 1
    enforce(total == len(devices),
            "mesh axes %s multiply to %d but %d devices are available",
            axes, total, len(devices))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def data_parallel_mesh(num_devices: Optional[int] = None) -> Mesh:
    n = num_devices or jax.device_count()
    return make_mesh({"dp": n}, jax.devices()[:n])


# THREAD-LOCAL ambient mesh: mesh-aware ops (ring/zigzag/ulysses/moe,
# and the sdpa sp routing) read it at trace time, and every trace
# happens in the thread that entered mesh_guard (CompiledProgram.run
# traces synchronously inside its guard). A process-global here would
# let one thread's mesh silently reroute an UNRELATED program being
# traced concurrently on another thread (a serving process hosting a
# mesh model next to a plain one) through a schedule it never opted
# into.
import threading as _threading

_mesh_tls = _threading.local()


def set_mesh(mesh: Optional[Mesh]):
    _mesh_tls.mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return getattr(_mesh_tls, "mesh", None)


@contextlib.contextmanager
def mesh_guard(mesh: Mesh):
    prev = current_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def named_sharding(mesh: Mesh, spec: Optional[PartitionSpec]
                   ) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None
                         else PartitionSpec())


def shard_batch_spec(ndim: int, axis_name: str = "dp") -> PartitionSpec:
    """Shard dim 0 (batch) over the data axis, replicate the rest."""
    return PartitionSpec(axis_name, *([None] * (ndim - 1)))


def first_divisible_dim(shape: Tuple[int, ...], parts: int
                        ) -> Optional[int]:
    for i, d in enumerate(shape):
        if d is not None and d > 0 and d % parts == 0:
            return i
    return None
