"""Multi-host (pod) runtime coordination.

Reference: the NCCL2 bootstrap — rank0 creates an ncclUniqueId and
serves it to peers over gRPC (gen_nccl_id_op.cc:31,162,179), then
ParallelExecutor runs num_trainers*ndev ranks (parallel_executor.cc:
319); trainer role env vars come from transpiler/fleet role makers.

TPU-native redesign: the PJRT distributed runtime replaces the
nccl-id handshake — ``jax.distributed.initialize(coordinator, n,
rank)`` is the gen_nccl_id analog; afterwards every process sees the
global device list, one Mesh spans the pod, and GSPMD collectives ride
ICI within a slice / DCN across slices (the MultiNCCLContextMap
hierarchy is expressed by mesh axis order: outer axes land on DCN).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax

from ..core.enforce import enforce
from .mesh import AXIS_ORDER, make_mesh

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """Bootstrap the multi-process runtime (reference: NCCL2 transpile
    mode + PADDLE_TRAINER_* env vars; here also the PADDLE_* spelling
    is honored for drop-in launch scripts)."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_COORDINATOR") or \
        _first_endpoint(os.environ.get("PADDLE_TRAINER_ENDPOINTS"))
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    _initialized = True


def _first_endpoint(endpoints):
    if not endpoints:
        return None
    return endpoints.split(",")[0]


def rank() -> int:
    return jax.process_index()


def world_size() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def global_device_count() -> int:
    return jax.device_count()


def pod_mesh(axes: Optional[Dict[str, int]] = None,
             inner_axis: str = "tp"):
    """A mesh spanning every device of every process, laid out
    hierarchically: devices are ordered process-major, so an outer axis
    of size ``n_processes`` crosses hosts (DCN) while inner axes stay
    within a host's chips (ICI) — the hierarchical-allreduce layout of
    the reference (MultiNCCLContextMap, nccl_helper.h:179).

    Without ``axes``, builds {"dp": n_processes, inner_axis:
    devices_per_process} so only data-parallel all-reduces cross DCN.
    With explicit ``axes``, sizes must multiply to the global device
    count; axes are nested in AXIS_ORDER with the process (DCN)
    boundary landing on the outermost axes."""
    n_proc = jax.process_count()
    per_proc = jax.local_device_count()
    # process-major ordering puts the host boundary on the outer axes
    devices = sorted(jax.devices(),
                     key=lambda d: (d.process_index, d.id))
    if axes is None:
        if n_proc > 1:
            axes = {"dp": n_proc, inner_axis: per_proc}
        else:
            axes = {"dp": per_proc}
    return make_mesh(axes, devices)
