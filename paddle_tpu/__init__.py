"""paddle_tpu — a TPU-native deep-learning framework with the
capabilities of PaddlePaddle Fluid (reference: /root/reference,
SunGaofeng/Paddle ~v1.4).

Architecture (vs the reference):
  - Users build a static ``Program`` of ops via ``layers.*`` — the same
    declarative workflow as fluid (python/paddle/fluid/framework.py).
  - The Executor traces the whole program through pure-JAX op lowerings
    into ONE XLA computation per step (instead of a C++ op-by-op
    interpreter, framework/executor.cc): params live in HBM and are
    donated, XLA fuses across op boundaries, collectives are
    compiler-inserted over ICI via mesh shardings (instead of NCCL op
    handles, framework/details/).
  - Autodiff appends generic vjp ops (backward.py) whose pullbacks come
    from jax.vjp of the forward lowerings (instead of per-op C++
    GradOpMakers).
  - Hot fused kernels (attention, layer_norm, optimizer updates) are
    pallas TPU kernels (ops/pallas/), the analog of operators/fused/ +
    operators/jit/.
"""

from . import core  # noqa: F401
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import unique_name  # noqa: F401
from . import backward  # noqa: F401
from .backward import append_backward, gradients  # noqa: F401
from .core import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa: F401
                   TPUPlace, global_scope)
from .core.scope import Scope  # noqa: F401
from .compiler import (BuildStrategy, CompiledProgram,  # noqa: F401
                       ExecutionStrategy)
from .executor import Executor, scope_guard  # noqa: F401
from . import parallel  # noqa: F401
from . import contrib  # noqa: F401
from . import install_check  # noqa: F401
from . import profiler  # noqa: F401
from . import dygraph  # noqa: F401
from . import average  # noqa: F401
from .average import WeightedAverage  # noqa: F401
from .parallel_executor import ParallelExecutor  # noqa: F401
from .framework import (cpu_places, cuda_pinned_places,  # noqa: F401
                        cuda_places)
from .initializer import force_init_on_cpu, init_on_cpu  # noqa: F401
from .framework import (Program, Variable, convert_dtype,  # noqa: F401
                        default_main_program, default_startup_program,
                        name_scope, program_guard)
from . import io  # noqa: F401
from . import compile_cache  # noqa: F401
from . import resilience  # noqa: F401
from . import incubate  # noqa: F401
from . import metrics  # noqa: F401
from . import nets  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import recordio  # noqa: F401
from .dataset_factory import (DatasetFactory, InMemoryDataset,  # noqa: F401
                              QueueDataset)
from .data_feeder import DataFeeder  # noqa: F401
from .pyreader import (DataLoader, DevicePrefetcher,  # noqa: F401
                       PyReader)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from . import ir  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import transpiler
from . import utils  # noqa: F401
from .reader import batch  # noqa: F401  (paddle.batch, __init__.py:29)
from . import debugger  # noqa: F401
from . import evaluator  # noqa: F401
from . import lod_tensor  # noqa: F401
from .lod_tensor import (create_lod_tensor,  # noqa: F401
                         create_random_int_lodtensor)  # noqa: F401
from .transpiler import (DistributeTranspiler,  # noqa: F401
                         DistributeTranspilerConfig, memory_optimize,
                         release_memory)
from .async_executor import AsyncExecutor  # noqa: F401
from .core import device_info  # noqa: F401

__version__ = "0.1.0"

# fluid-compat alias so reference user scripts port by renaming only the
# import: ``import paddle_tpu as fluid``.
