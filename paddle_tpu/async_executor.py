"""AsyncExecutor: the legacy file-driven CTR training front end.

Reference: python/paddle/fluid/async_executor.py (fluid.AsyncExecutor)
over paddle/fluid/framework/async_executor.cc:68 RunFromFile —
thread-per-core workers each consuming a shard of a file list through a
DataFeed and running the program lock-free (the predecessor of the
trainer/device-worker path, which the reference itself migrated to).

TPU-native: the thread pool dissolves — the Dataset's reader threads
shard/parse files on the host while ONE compiled XLA step consumes the
batches (Executor.train_from_dataset). This facade keeps the legacy
surface so AsyncExecutor scripts run unchanged.
"""

from __future__ import annotations

from typing import List, Optional

from .core.enforce import InvalidArgumentError, enforce
from .dataset_factory import DatasetFactory
from .executor import Executor
from .framework import Program


class AsyncExecutor:
    """Reference: async_executor.py AsyncExecutor.__init__(place,
    run_mode)."""

    def __init__(self, place=None, run_mode=""):
        self.place = place
        self.run_mode = run_mode
        self.executor = Executor(place)

    def run(self, program: Program, data_feed, filelist: List[str],
            thread_num: int = 1, fetch: Optional[list] = None,
            mode="", debug=False):
        """RunFromFile analog (async_executor.cc:68): build an
        in-memory Dataset over ``filelist`` described by ``data_feed``
        (a DataFeedDesc-like object or a dict with slot vars + batch
        size) and drive train_from_dataset. ``thread_num`` maps to the
        Dataset's reader-thread count."""
        enforce(filelist, "AsyncExecutor.run needs a non-empty filelist")
        if hasattr(data_feed, "to_dataset"):
            dataset = data_feed.to_dataset()
        elif isinstance(data_feed, dict):
            dataset = DatasetFactory().create_dataset("InMemoryDataset")
            dataset.set_batch_size(data_feed.get("batch_size", 64))
            dataset.set_use_var(data_feed["use_var"])
            if "pipe_command" in data_feed:
                dataset.set_pipe_command(data_feed["pipe_command"])
        else:
            raise InvalidArgumentError(
                "data_feed must be a dict(batch_size=, use_var=[vars]) "
                "or expose .to_dataset()")
        dataset.set_thread(max(int(thread_num), 1))
        dataset.set_filelist(list(filelist))
        dataset.load_into_memory()
        return self.executor.train_from_dataset(
            program=program, dataset=dataset, debug=debug,
            fetch_list=fetch or [])

    # legacy fleet hooks kept for surface parity; the real distributed
    # path lives in incubate.fleet + distributed (PS runtime)
    def config_distributed_nodes(self):
        raise InvalidArgumentError(
            "AsyncExecutor distributed mode was superseded by "
            "fleet (incubate.fleet) in the reference too; use "
            "fleet.init + distributed.PServerRuntime")
