"""DataFeeder: rows of python/numpy data → feed dict of batched arrays
(reference: python/paddle/fluid/data_feeder.py — DataFeeder:272,
DataToLoDTensorConverter:50).

The reference converts to LoDTensors; here ragged samples are padded to
the declared static shape (TPU wants static shapes — SURVEY §7 "LoD →
pad + mask"), and an optional mask slot reports true lengths."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .core.enforce import enforce
from .framework import Variable

_DTYPE_DEFAULT = {"float32": np.float32, "float64": np.float64,
                  "int32": np.int32, "int64": np.int64,
                  "bool": np.bool_, "float16": np.float16,
                  "bfloat16": np.float32}


class DataFeeder:
    """feed_list: Variables (or names looked up in ``program``)."""

    def __init__(self, feed_list: Sequence, place=None, program=None):
        from .framework import default_main_program
        program = program or default_main_program()
        self.feed_vars: List[Variable] = []
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            enforce(isinstance(v, Variable), "feed_list entries must be "
                    "Variables or names")
            self.feed_vars.append(v)
        self.place = place

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        """iterable: list of rows, each row one value per feed var."""
        columns = [[] for _ in self.feed_vars]
        n_rows = 0
        for row in iterable:
            enforce(len(row) == len(self.feed_vars),
                    "row has %d fields, feeder expects %d"
                    % (len(row), len(self.feed_vars)))
            for c, value in zip(columns, row):
                c.append(value)
            n_rows += 1
        out = {}
        for var, col in zip(self.feed_vars, columns):
            out[var.name] = self._to_batch_array(var, col)
        return out

    def _to_batch_array(self, var, col):
        np_dtype = _DTYPE_DEFAULT.get(var.dtype, np.float32)
        # static per-sample shape from the declaration (skip batch dim)
        decl = [d for d in var.shape if d != -1]
        arrs = [np.asarray(v, dtype=np_dtype) for v in col]
        if decl:
            # scalars / flat rows that exactly fill the declared shape
            # are reshaped (fluid reshapes to the declared dims too)
            arrs = [a.reshape(decl)
                    if a.size == int(np.prod(decl)) and
                    tuple(a.shape) != tuple(decl) else a
                    for a in arrs]
        if decl and any(tuple(a.shape) != tuple(decl) for a in arrs):
            # ragged → pad to the declared static shape
            batch = np.zeros((len(arrs),) + tuple(decl), np_dtype)
            for i, a in enumerate(arrs):
                enforce(a.ndim == len(decl),
                        "sample rank %d != declared rank %d for %r"
                        % (a.ndim, len(decl), var.name))
                enforce(all(s <= d for s, d in zip(a.shape, decl)),
                        "sample shape %s exceeds declared static shape "
                        "%s for %r — samples are padded up, never "
                        "truncated; declare a larger shape or bucket "
                        "the data" % (tuple(a.shape), tuple(decl),
                                      var.name))
                sl = tuple(slice(0, s) for s in a.shape)
                batch[(i,) + sl] = a
            return batch
        return np.stack(arrs)


def convert_numpy(value, dtype):
    return np.asarray(value, dtype=_DTYPE_DEFAULT.get(dtype, np.float32))
