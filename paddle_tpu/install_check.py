"""Installation smoke check.

Reference: python/paddle/fluid/install_check.py — ``run_check()``
builds a tiny fc model, runs one forward+backward, and prints a
success message so users can verify the install end to end (program
build, startup, trace, compile, execute, autodiff)."""

from __future__ import annotations

import numpy as np

from . import executor, framework, layers, optimizer, unique_name
from .core.scope import Scope

__all__ = ["run_check"]


def run_check():
    """Verify the framework end to end on whatever backend JAX sees
    (reference install_check.py:42)."""
    print("Running verify paddle_tpu program ... ")
    prog = framework.Program()
    startup = framework.Program()
    scope = Scope()
    with executor.scope_guard(scope):
        with framework.program_guard(prog, startup):
            with unique_name.guard():
                inp = layers.data(name="inp", shape=[2, 2],
                                  append_batch_size=False)
                out = layers.fc(inp, size=2)
                loss = layers.reduce_mean(out)
                optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = executor.Executor()
        exe.run(startup)
        np_inp = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        (lv,) = exe.run(prog, feed={"inp": np_inp},
                        fetch_list=[loss])
        if not np.isfinite(np.asarray(lv)).all():
            raise RuntimeError(
                "install check produced a non-finite loss: %r" % lv)
    print("Your paddle_tpu is installed successfully! Training and "
          "autodiff work on this backend.")
