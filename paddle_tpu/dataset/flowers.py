"""Oxford-102 flowers reader creators.

Reference: python/paddle/dataset/flowers.py — train()/test()/valid()
yield (CHW float32 image pushed through simple_transform, int64
label in [0, 102)).

Real data under ``DATA_HOME/flowers/``: ``102flowers.tgz``
(jpg/image_%05d.jpg), ``imagelabels.mat`` and ``setid.mat`` — parsed
the reference way (flowers.py:108-120: setid's tstid drives train and
trnid drives test, the reference's deliberate swap; labels are 1-based
in the .mat and 0-based here to match the synthetic contract).
Synthetic fallback: class-conditional color blobs run through the
SAME image.py transform pipeline so the full preprocessing path is
exercised.
"""

from __future__ import annotations

import tarfile

import numpy as np

from . import common
from . import image as img_util

__all__ = ["train", "test", "valid"]

N_CLASSES = 102
TRAIN_SIZE = 1024
TEST_SIZE = 256
VALID_SIZE = 256

_DATA = "102flowers.tgz"
_LABELS = "imagelabels.mat"
_SETID = "setid.mat"
# the reference swaps train/test on purpose (flowers.py:55-60)
TRAIN_FLAG = "tstid"
TEST_FLAG = "trnid"
VALID_FLAG = "valid"


def _raw(idx):
    rng = np.random.RandomState(idx)
    label = idx % N_CLASSES
    h, w = int(rng.randint(160, 320)), int(rng.randint(160, 320))
    img = rng.randint(0, 40, size=(h, w, 3)).astype(np.uint8)
    # class-coded dominant color patch
    img[h // 4:3 * h // 4, w // 4:3 * w // 4, label % 3] += np.uint8(
        120 + (label * 7) % 100)
    return img, np.int64(label)


def _creator(n, base, is_train, mapper=None):
    def reader():
        for i in range(n):
            raw, label = _raw(base + i)
            rng = np.random.RandomState(base + i + 1)
            out = img_util.simple_transform(
                raw, 256, 224, is_train, mean=[104.0, 117.0, 124.0],
                rng=rng)
            if mapper is not None:
                out = mapper(out)
            yield out, label

    return reader


def _have_real():
    return all(common.have_file("flowers", f)
               for f in (_DATA, _LABELS, _SETID))


def _real_creator(flag, is_train, mapper=None):
    # augmentation must differ across epochs: seed per (epoch, image),
    # not per image, or every epoch replays identical crops/flips
    epoch = {"n": 0}

    def reader():
        import io as _io

        import scipy.io as scio
        from PIL import Image

        labels = scio.loadmat(
            common.data_path("flowers", _LABELS))["labels"][0]
        indexes = scio.loadmat(
            common.data_path("flowers", _SETID))[flag][0]
        wanted = {"jpg/image_%05d.jpg" % i: int(i) for i in indexes}
        # ONE sequential pass over the gzip tar: random-access
        # extractfile in setid order would rewind and re-decompress
        # the ~330MB stream on every backward seek. Samples therefore
        # come out in archive order (the reference shuffles its batch
        # files anyway, flowers.py:121).
        epoch["n"] += 1
        with tarfile.open(common.data_path("flowers", _DATA)) as tf:
            member = tf.next()
            while member is not None:
                i = wanted.get(member.name)
                if i is not None:
                    blob = tf.extractfile(member).read()
                    raw = np.asarray(Image.open(_io.BytesIO(blob))
                                     .convert("RGB"), np.uint8)
                    rng = np.random.RandomState(
                        (epoch["n"] * 1_000_003 + i) & 0x7FFFFFFF)
                    out = img_util.simple_transform(
                        raw, 256, 224, is_train,
                        mean=[104.0, 117.0, 124.0], rng=rng)
                    if mapper is not None:
                        out = mapper(out)
                    yield out, np.int64(int(labels[i - 1]) - 1)
                member = tf.next()

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    if _have_real():
        return _real_creator(TRAIN_FLAG, True, mapper)
    return _creator(TRAIN_SIZE, 0, True, mapper)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    if _have_real():
        return _real_creator(TEST_FLAG, False, mapper)
    return _creator(TEST_SIZE, 13_000_000, False, mapper)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    if _have_real():
        return _real_creator(VALID_FLAG, False, mapper)
    return _creator(VALID_SIZE, 14_000_000, False, mapper)
