"""Oxford-102 flowers reader creators.

Reference: python/paddle/dataset/flowers.py — train()/test()/valid()
yield (CHW float32 image pushed through simple_transform, int64
label in [0, 102)). Synthetic fallback: class-conditional color blobs
run through the SAME image.py transform pipeline so the full
preprocessing path is exercised.
"""

from __future__ import annotations

import numpy as np

from . import image as img_util

__all__ = ["train", "test", "valid"]

N_CLASSES = 102
TRAIN_SIZE = 1024
TEST_SIZE = 256
VALID_SIZE = 256


def _raw(idx):
    rng = np.random.RandomState(idx)
    label = idx % N_CLASSES
    h, w = int(rng.randint(160, 320)), int(rng.randint(160, 320))
    img = rng.randint(0, 40, size=(h, w, 3)).astype(np.uint8)
    # class-coded dominant color patch
    img[h // 4:3 * h // 4, w // 4:3 * w // 4, label % 3] += np.uint8(
        120 + (label * 7) % 100)
    return img, np.int64(label)


def _creator(n, base, is_train, mapper=None):
    def reader():
        for i in range(n):
            raw, label = _raw(base + i)
            rng = np.random.RandomState(base + i + 1)
            out = img_util.simple_transform(
                raw, 256, 224, is_train, mean=[104.0, 117.0, 124.0],
                rng=rng)
            if mapper is not None:
                out = mapper(out)
            yield out, label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator(TRAIN_SIZE, 0, True, mapper)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator(TEST_SIZE, 13_000_000, False, mapper)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _creator(VALID_SIZE, 14_000_000, False, mapper)
