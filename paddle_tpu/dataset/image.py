"""Image preprocessing utilities (pure numpy).

Reference: python/paddle/dataset/image.py (load_image, resize_short,
center_crop, random_crop, left_right_flip, simple_transform,
load_and_transform — there via cv2). TPU-native note: these run in
the host data pipeline feeding the device; numpy keeps them
dependency-free (cv2 is a vendor library the reference dynloads).
Images are HWC uint8/float arrays; ``to_chw`` transposes for the
NCHW-consuming conv models.
"""

from __future__ import annotations

import numpy as np

__all__ = ["load_image", "resize_short", "center_crop", "random_crop",
           "left_right_flip", "to_chw", "simple_transform",
           "load_and_transform", "batch_images"]


def load_image(path, is_color=True):
    """Decode an image file to an HWC uint8 array. Uses PIL when
    available; raises a clear error otherwise (zero-egress images are
    usually provisioned as .npy — np.load is always supported)."""
    if str(path).endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError(
            "decoding %r needs PIL; provision .npy arrays instead"
            % path) from e
    img = Image.open(path)
    img = img.convert("RGB" if is_color else "L")
    arr = np.asarray(img, dtype=np.uint8)
    if not is_color:
        arr = arr[:, :, None]
    return arr


def _resize(img, h, w):
    """Nearest-neighbor resize (numpy): adequate for pipeline tests
    and synthetic data; swap in PIL/cv2 for production quality."""
    hh = (np.arange(h) * (img.shape[0] / h)).astype(int)
    ww = (np.arange(w) * (img.shape[1] / w)).astype(int)
    return img[hh][:, ww]


def resize_short(img, size):
    """Scale so the SHORT side equals ``size`` (reference:
    image.py resize_short)."""
    h, w = img.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    return _resize(img, nh, nw)


def center_crop(img, size, is_color=True):
    h, w = img.shape[:2]
    top = (h - size) // 2
    left = (w - size) // 2
    return img[top:top + size, left:left + size]


def random_crop(img, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = img.shape[:2]
    top = int(rng.randint(0, h - size + 1))
    left = int(rng.randint(0, w - size + 1))
    return img[top:top + size, left:left + size]


def left_right_flip(img, is_color=True):
    return img[:, ::-1]


def to_chw(img, order=(2, 0, 1)):
    return img.transpose(order)


def simple_transform(img, resize_size, crop_size, is_train,
                     is_color=True, mean=None, rng=None):
    """resize_short -> crop (random+flip when training, center
    otherwise) -> CHW float32 -> mean subtraction (reference:
    image.py simple_transform)."""
    img = resize_short(img, resize_size)
    if is_train:
        img = random_crop(img, crop_size, rng=rng)
        if (rng or np.random).randint(2):
            img = left_right_flip(img)
    else:
        img = center_crop(img, crop_size)
    img = to_chw(img).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        img -= mean.reshape(-1, 1, 1) if mean.ndim == 1 else mean
    return img


def load_and_transform(path, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images(samples):
    """Stack (img, label) samples into (batch NCHW, labels [n, 1])."""
    imgs = np.stack([s[0] for s in samples]).astype(np.float32)
    labels = np.asarray([s[1] for s in samples],
                        np.int64).reshape(-1, 1)
    return imgs, labels
