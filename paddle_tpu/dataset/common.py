"""Dataset cache / checksum / download protocol.

Reference: python/paddle/dataset/common.py (DATA_HOME, download() with
md5 verification and retry, md5file). This environment has zero
egress, so network fetch is GATED: ``download`` uses a file already
present in the cache dir (checksum-verified) and otherwise raises a
clear error telling the user how to provision the file — unless
``PADDLE_TPU_ALLOW_DOWNLOAD=1`` explicitly enables urllib fetching.
Every loader degrades to its deterministic synthetic generator when
the real files are absent, so models and tests run everywhere.
"""

from __future__ import annotations

import hashlib
import os

__all__ = ["DATA_HOME", "data_path", "md5file", "download",
           "have_file", "DownloadUnavailableError"]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "dataset"))


class DownloadUnavailableError(RuntimeError):
    pass


def data_path(module, filename):
    return os.path.join(DATA_HOME, module, filename)


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def have_file(module, filename, md5=None):
    path = data_path(module, filename)
    if not os.path.exists(path):
        return False
    return md5 is None or md5file(path) == md5


def download(url, module, md5=None, filename=None):
    """Return the local path of ``url``'s file under
    ``DATA_HOME/module/``, verifying md5 when given. Fetches over the
    network only when PADDLE_TPU_ALLOW_DOWNLOAD=1 (reference:
    common.py:download retries 3x with md5 check)."""
    filename = filename or url.split("/")[-1].split("?")[0]
    path = data_path(module, filename)
    if os.path.exists(path):
        if md5 is None or md5file(path) == md5:
            return path
        os.remove(path)
    if os.environ.get("PADDLE_TPU_ALLOW_DOWNLOAD") != "1":
        raise DownloadUnavailableError(
            "dataset file %r is not cached and downloads are disabled "
            "(zero-egress environment). Place the file at %s (md5 %s) "
            "or set PADDLE_TPU_ALLOW_DOWNLOAD=1."
            % (filename, path, md5 or "unchecked"))
    import urllib.request
    os.makedirs(os.path.dirname(path), exist_ok=True)
    for attempt in range(3):
        urllib.request.urlretrieve(url, path)
        if md5 is None or md5file(path) == md5:
            return path
    raise DownloadUnavailableError(
        "md5 mismatch for %s after 3 attempts" % url)
