"""PTB (imikolov) language-model reader creators.

Reference: python/paddle/dataset/imikolov.py — build_dict(min_word_freq)
over the corpus; train(word_idx, n)/test(word_idx, n) yield n-gram
tuples (DataType.NGRAM) or (src_seq, trg_seq) pairs (DataType.SEQ)
with <s>/<e>/<unk> handling.

Real data: drop ``simple-examples.tgz`` under ``DATA_HOME/imikolov/``
and the PTB text inside (``./simple-examples/data/ptb.train.txt`` /
``ptb.valid.txt``) is parsed exactly as the reference does
(imikolov.py:40-107: word-frequency dict with ``freq > min_word_freq``
cutoff, <unk> appended last, sliding n-grams / <s>-<e> seq pairs).
Synthetic fallback: Zipf-distributed deterministic sentences.
"""

from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["DataType", "build_dict", "train", "test"]

_VOCAB = 2048
_TRAIN_SENTENCES = 2048
_TEST_SENTENCES = 256

_ARCHIVE = "simple-examples.tgz"
_TRAIN_MEMBER = "./simple-examples/data/ptb.train.txt"
_TEST_MEMBER = "./simple-examples/data/ptb.valid.txt"


class DataType:
    NGRAM = 1
    SEQ = 2


def _sentence(idx):
    rng = np.random.RandomState(idx)
    n = int(rng.randint(3, 20))
    # Zipf-ish: low ids frequent
    ids = (rng.zipf(1.3, size=n) - 1) % (_VOCAB - 3)
    return ["w%d" % i for i in ids]


def _have_real():
    return common.have_file("imikolov", _ARCHIVE)


def _real_sentences(member):
    with tarfile.open(common.data_path("imikolov", _ARCHIVE)) as tf:
        f = tf.extractfile(member)
        for line in f:
            yield line.decode("utf-8", "replace").strip().split()


def build_dict(min_word_freq=50):
    """word -> id with <unk> last (reference: imikolov.py:40-64 counts
    train+test, drops <unk>, keeps ``freq > min_word_freq``, sorts by
    (-freq, word))."""
    freq = {}
    if _have_real():
        for member in (_TRAIN_MEMBER, _TEST_MEMBER):
            for words in _real_sentences(member):
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        keep = [w for w, c in freq.items() if c > min_word_freq]
    else:
        for i in range(_TRAIN_SENTENCES):
            for w in _sentence(i):
                freq[w] = freq.get(w, 0) + 1
        keep = [w for w, c in freq.items() if c >= min_word_freq]
    words = sorted(keep, key=lambda w: (-freq[w], w))
    word_idx = {w: i for i, w in enumerate(words)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _emit(words, word_idx, n, data_type):
    """One sentence -> samples (reference imikolov.py:84-107)."""
    unk = word_idx["<unk>"]
    start = word_idx.get("<s>", unk)
    end = word_idx.get("<e>", unk)
    if data_type == DataType.NGRAM:
        l = [start] + [word_idx.get(w, unk) for w in words] + [end]
        if len(l) >= n:
            for j in range(n, len(l) + 1):
                yield tuple(l[j - n:j])
    else:
        ids = [word_idx.get(w, unk) for w in words]
        src = [start] + ids
        if n > 0 and len(src) > n:
            return
        yield src, ids + [end]


def _creator(n_sent, base, word_idx, n, data_type):
    def reader():
        for i in range(n_sent):
            for s in _emit(_sentence(base + i), word_idx, n, data_type):
                yield s

    return reader


def _real_creator(member, word_idx, n, data_type):
    def reader():
        for words in _real_sentences(member):
            for s in _emit(words, word_idx, n, data_type):
                yield s

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    if _have_real():
        return _real_creator(_TRAIN_MEMBER, word_idx, n, data_type)
    return _creator(_TRAIN_SENTENCES, 0, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    if _have_real():
        return _real_creator(_TEST_MEMBER, word_idx, n, data_type)
    return _creator(_TEST_SENTENCES, 9_000_000, word_idx, n, data_type)
