"""PTB (imikolov) language-model reader creators.

Reference: python/paddle/dataset/imikolov.py — build_dict(min_word_freq)
over the corpus; train(word_idx, n)/test(word_idx, n) yield n-gram
tuples (DataType.NGRAM) or (src_seq, trg_seq) pairs (DataType.SEQ)
with <s>/<e>/<unk> handling. Synthetic corpus: Zipf-distributed
deterministic sentences.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DataType", "build_dict", "train", "test"]

_VOCAB = 2048
_TRAIN_SENTENCES = 2048
_TEST_SENTENCES = 256


class DataType:
    NGRAM = 1
    SEQ = 2


def _sentence(idx):
    rng = np.random.RandomState(idx)
    n = int(rng.randint(3, 20))
    # Zipf-ish: low ids frequent
    ids = (rng.zipf(1.3, size=n) - 1) % (_VOCAB - 3)
    return ["w%d" % i for i in ids]


def build_dict(min_word_freq=50):
    """word -> id with <s>, <e>, <unk> (reference: imikolov.py:53)."""
    freq = {}
    for i in range(_TRAIN_SENTENCES):
        for w in _sentence(i):
            freq[w] = freq.get(w, 0) + 1
    words = sorted((w for w, c in freq.items() if c >= min_word_freq),
                   key=lambda w: (-freq[w], w))
    word_idx = {w: i for i, w in enumerate(words)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _creator(n_sent, base, word_idx, n, data_type):
    def reader():
        unk = word_idx["<unk>"]
        start = word_idx.get("<s>", unk)
        end = word_idx.get("<e>", unk)
        for i in range(n_sent):
            words = _sentence(base + i)
            if data_type == DataType.NGRAM:
                l = [start] + [word_idx.get(w, unk) for w in words] \
                    + [end]
                if len(l) < n:
                    continue
                for j in range(n, len(l) + 1):
                    yield tuple(l[j - n:j])
            else:
                ids = [word_idx.get(w, unk) for w in words]
                yield [start] + ids, ids + [end]

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _creator(_TRAIN_SENTENCES, 0, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _creator(_TEST_SENTENCES, 9_000_000, word_idx, n, data_type)
