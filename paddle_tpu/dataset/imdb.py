"""IMDB sentiment reader creators (reference:
python/paddle/dataset/imdb.py — word-id sequences + 0/1 label).
Synthetic: positive samples draw from one token range, negative from
another, variable length (exercises the pad/bucket pipeline)."""

from __future__ import annotations

import numpy as np

VOCAB_SIZE = 5148  # reference's imdb.word_dict() size ballpark
TRAIN_SIZE = 2048
TEST_SIZE = 256


def word_dict():
    return {("w%d" % i).encode(): i for i in range(VOCAB_SIZE - 2)}


def _sample(idx):
    rng = np.random.RandomState(idx)
    label = idx % 2
    n = int(rng.randint(8, 120))
    lo, hi = (0, VOCAB_SIZE // 2) if label else (VOCAB_SIZE // 2,
                                                 VOCAB_SIZE - 2)
    ids = rng.randint(lo, hi, size=n).astype(np.int64)
    return ids, np.int64(label)


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def train(word_idx=None):
    return _creator(TRAIN_SIZE, 0)


def test(word_idx=None):
    return _creator(TEST_SIZE, 3_000_000)
