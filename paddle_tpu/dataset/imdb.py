"""IMDB sentiment reader creators (reference:
python/paddle/dataset/imdb.py — word-id sequences + 0/1 label, where
POSITIVE docs are label 0 and negative label 1, imdb.py:79-87).

Real data: drop ``aclImdb_v1.tar.gz`` under ``DATA_HOME/imdb/`` and
the per-document text members are tokenized the reference way
(imdb.py:39-55: sequential tar scan, punctuation stripped, lowered,
split; build_dict keeps ``freq > cutoff`` sorted by (-freq, word) with
<unk> last). Synthetic fallback: positive samples draw from one token
range, negative from another, variable length (exercises the
pad/bucket pipeline)."""

from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from . import common

__all__ = ["build_dict", "word_dict", "train", "test"]

VOCAB_SIZE = 5148  # reference's imdb.word_dict() size ballpark
TRAIN_SIZE = 2048
TEST_SIZE = 256

_ARCHIVE = "aclImdb_v1.tar.gz"
_PUNCT_TABLE = bytes.maketrans(b"", b"")  # identity; deleted chars below
_PUNCT = string.punctuation.encode()


def _have_real():
    return common.have_file("imdb", _ARCHIVE)


def tokenize(pattern):
    """Yield one token list per tar member matching ``pattern``
    (reference imdb.py:39-55, sequential next() scan)."""
    with tarfile.open(common.data_path("imdb", _ARCHIVE)) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                yield tarf.extractfile(tf).read().rstrip(b"\n\r") \
                    .translate(_PUNCT_TABLE, _PUNCT).lower().split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    """bytes word -> id over matching docs (reference imdb.py:58-74)."""
    freq = {}
    for doc in tokenize(pattern):
        for w in doc:
            freq[w] = freq.get(w, 0) + 1
    keep = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                  key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _c) in enumerate(keep)}
    word_idx[b"<unk>"] = len(word_idx)
    return word_idx


def word_dict():
    if _have_real():
        return build_dict(
            re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
            150)
    return {("w%d" % i).encode(): i for i in range(VOCAB_SIZE - 2)}


def _sample(idx):
    rng = np.random.RandomState(idx)
    label = idx % 2
    n = int(rng.randint(8, 120))
    lo, hi = (0, VOCAB_SIZE // 2) if label else (VOCAB_SIZE // 2,
                                                 VOCAB_SIZE - 2)
    ids = rng.randint(lo, hi, size=n).astype(np.int64)
    return ids, np.int64(label)


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def _real_creator(pos_pattern, neg_pattern, word_idx):
    """Load all docs then yield; pos docs are label 0 (reference
    imdb.py:79-96)."""
    def reader():
        unk = word_idx[b"<unk>"]
        for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
            for doc in tokenize(pattern):
                yield [word_idx.get(w, unk) for w in doc], label

    return reader


def _require_word_idx(word_idx):
    """With the real archive present, a missing word_idx must not
    silently fall back to synthetic ids from a different id space."""
    if word_idx is None:
        raise ValueError(
            "the real aclImdb archive is present; pass word_idx "
            "(e.g. imdb.word_dict()) so sample ids match the vocab")
    return word_idx


def train(word_idx=None):
    if _have_real():
        return _real_creator(
            re.compile(r"aclImdb/train/pos/.*\.txt$"),
            re.compile(r"aclImdb/train/neg/.*\.txt$"),
            _require_word_idx(word_idx))
    return _creator(TRAIN_SIZE, 0)


def test(word_idx=None):
    if _have_real():
        return _real_creator(
            re.compile(r"aclImdb/test/pos/.*\.txt$"),
            re.compile(r"aclImdb/test/neg/.*\.txt$"),
            _require_word_idx(word_idx))
    return _creator(TEST_SIZE, 3_000_000)
