"""MQ2007 LETOR learning-to-rank reader creators.

Reference: python/paddle/dataset/mq2007.py — train(format=...)/test:
``pointwise`` yields (feature_vector[46], relevance); ``pairwise``
yields (d_high[46], d_low[46]); ``listwise`` yields per-query
(label_list, feature_matrix). Synthetic queries embed relevance
linearly in a feature subspace so rankers actually learn.
"""

from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "FEATURE_DIM"]

FEATURE_DIM = 46
_TRAIN_QUERIES = 256
_TEST_QUERIES = 64


def _query(idx):
    rng = np.random.RandomState(idx)
    n_docs = int(rng.randint(5, 20))
    feats = rng.rand(n_docs, FEATURE_DIM).astype(np.float32)
    score = feats[:, :5].sum(axis=1) + rng.randn(n_docs) * 0.1
    rel = np.digitize(score, np.quantile(score, [0.5, 0.8]))
    return rel.astype(np.int64), feats


def _creator(n, base, fmt):
    def reader():
        for i in range(n):
            rel, feats = _query(base + i)
            if fmt == "listwise":
                yield rel.tolist(), feats
            elif fmt == "pointwise":
                for r, f in zip(rel, feats):
                    yield f, int(r)
            else:  # pairwise
                for a in range(len(rel)):
                    for b in range(len(rel)):
                        if rel[a] > rel[b]:
                            yield feats[a], feats[b]

    return reader


def train(format="pairwise"):
    return _creator(_TRAIN_QUERIES, 0, format)


def test(format="pairwise"):
    return _creator(_TEST_QUERIES, 17_000_000, format)
