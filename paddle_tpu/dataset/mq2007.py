"""MQ2007 LETOR learning-to-rank reader creators.

Reference: python/paddle/dataset/mq2007.py — train(format=...)/test:
``pointwise`` yields (feature_vector[46], relevance); ``pairwise``
yields (d_high[46], d_low[46]); ``listwise`` yields per-query
(label_list, feature_matrix).

Real data: the reference ships MQ2007 as a .rar (mq2007.py:34) which
the stdlib can't open, so drop the EXTRACTED fold files instead —
``MQ2007/Fold1/train.txt`` / ``test.txt`` under ``DATA_HOME/mq2007/``
— and the LETOR lines ("rel qid:<q> 1:<v> ... 46:<v> #docid...") are
parsed grouped by query (mq2007.py:89-120 Query.complete_, :269
load_from_text). Synthetic fallback: queries embed relevance linearly
in a feature subspace so rankers actually learn.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "FEATURE_DIM"]

FEATURE_DIM = 46
_TRAIN_QUERIES = 256
_TEST_QUERIES = 64

_TRAIN_FILE = "MQ2007/Fold1/train.txt"
_TEST_FILE = "MQ2007/Fold1/test.txt"


def _query(idx):
    rng = np.random.RandomState(idx)
    n_docs = int(rng.randint(5, 20))
    feats = rng.rand(n_docs, FEATURE_DIM).astype(np.float32)
    score = feats[:, :5].sum(axis=1) + rng.randn(n_docs) * 0.1
    rel = np.digitize(score, np.quantile(score, [0.5, 0.8]))
    return rel.astype(np.int64), feats


def _parse_letor(path, fill_missing=-1.0):
    """Group LETOR lines by qid, preserving file order (reference
    mq2007.py:89-120: rel, qid:<id>, then <fid>:<value> pairs;
    missing feature ids filled with ``fill_missing``)."""
    queries = []          # [(qid, [rel], [feat_vec])] in first-seen order
    by_qid = {}
    with open(path, "r") as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            qid = int(parts[1].split(":")[1])
            feat = np.full(FEATURE_DIM, fill_missing, np.float32)
            for p in parts[2:]:
                fid, val = p.split(":")
                fid = int(fid)
                if 1 <= fid <= FEATURE_DIM:
                    feat[fid - 1] = float(val)
            if qid not in by_qid:
                by_qid[qid] = ([], [])
                queries.append(qid)
            by_qid[qid][0].append(rel)
            by_qid[qid][1].append(feat)
    for qid in queries:
        rels, feats = by_qid[qid]
        yield (np.asarray(rels, np.int64),
               np.stack(feats).astype(np.float32))


def _emit(rel, feats, fmt):
    if fmt == "listwise":
        yield rel.tolist(), feats
    elif fmt == "pointwise":
        for r, f in zip(rel, feats):
            yield f, int(r)
    else:  # pairwise
        for a in range(len(rel)):
            for b in range(len(rel)):
                if rel[a] > rel[b]:
                    yield feats[a], feats[b]


def _creator(n, base, fmt):
    def reader():
        for i in range(n):
            rel, feats = _query(base + i)
            for s in _emit(rel, feats, fmt):
                yield s

    return reader


def _real_creator(filename, fmt):
    def reader():
        path = common.data_path("mq2007", filename)
        for rel, feats in _parse_letor(path):
            for s in _emit(rel, feats, fmt):
                yield s

    return reader


def train(format="pairwise"):
    if common.have_file("mq2007", _TRAIN_FILE):
        return _real_creator(_TRAIN_FILE, format)
    return _creator(_TRAIN_QUERIES, 0, format)


def test(format="pairwise"):
    if common.have_file("mq2007", _TEST_FILE):
        return _real_creator(_TEST_FILE, format)
    return _creator(_TEST_QUERIES, 17_000_000, format)
