"""Criteo-style CTR reader creators (reference: the dist_ctr test data
and models-repo criteo dataset: 13 dense + 26 sparse slots + click).

Real data: drop the classic Criteo display-advertising TSV
(``train.txt`` / ``test.txt``: label \\t 13 integer features \\t 26
hex-hashed categoricals, empty fields allowed) under
``DATA_HOME/criteo/``. Integers are log-transformed
(log(x+1), negatives clamped to 0) and categoricals hash into
``SPARSE_DIM`` buckets — the standard DeepFM preprocessing. Synthetic,
learnable, deterministic fallback otherwise."""

from __future__ import annotations

import zlib

import numpy as np

from . import common

NUM_DENSE = 13
NUM_SPARSE = 26
SPARSE_DIM = 100000
TRAIN_SIZE = 4096
TEST_SIZE = 512

_TRAIN_FILE = "train.txt"
_TEST_FILE = "test.txt"


def _sample(idx):
    rs = np.random.RandomState(idx)
    dense = rs.rand(NUM_DENSE).astype(np.float32)
    sparse = rs.randint(0, SPARSE_DIM, size=NUM_SPARSE).astype(np.int64)
    hot = (sparse < SPARSE_DIM // 20).any()
    p = 0.15 + 0.5 * hot + 0.3 * (dense[0] > 0.5)
    label = np.int64(rs.rand() < p)
    return dense, sparse, label


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def _parse_line(line, has_label=True):
    """One TSV line -> (dense[13] f32, sparse[26] i64, label i64).

    Missing integer fields become 0 before the log transform; missing
    categoricals hash the empty string (a stable OOV bucket)."""
    parts = line.rstrip("\n").split("\t")
    off = 1 if has_label else 0
    label = np.int64(int(parts[0])) if has_label else np.int64(0)
    dense = np.zeros(NUM_DENSE, np.float32)
    for i in range(NUM_DENSE):
        f = parts[off + i] if off + i < len(parts) else ""
        if f:
            v = float(f)
            dense[i] = np.log1p(max(v, 0.0))
    sparse = np.zeros(NUM_SPARSE, np.int64)
    for i in range(NUM_SPARSE):
        f = parts[off + NUM_DENSE + i] \
            if off + NUM_DENSE + i < len(parts) else ""
        # crc32: stable across runs/processes (hash() is seeded) and
        # C-speed on the 26x-per-row hot path
        sparse[i] = zlib.crc32(f.encode()) % SPARSE_DIM
    return dense, sparse, label


def _real_creator(filename, has_label=True):
    def reader():
        path = common.data_path("criteo", filename)
        with open(path) as f:
            for line in f:
                if line.strip():
                    yield _parse_line(line, has_label=has_label)

    return reader


def train():
    if common.have_file("criteo", _TRAIN_FILE):
        return _real_creator(_TRAIN_FILE)
    return _creator(TRAIN_SIZE, 0)


def test():
    if common.have_file("criteo", _TEST_FILE):
        # the public test.txt ships unlabeled (39 fields); a
        # provisioned labeled split (40 fields) works too. Labeledness
        # is fundamentally ambiguous from content alone (criteo's
        # first integer feature is often 0/1 too, and preprocessors
        # may trim trailing empty fields), so: explicit override via
        # PADDLE_TPU_CRITEO_TEST_LABELED=0/1 wins; otherwise the
        # verdict needs BOTH majorities over the first 100 non-blank
        # lines — most rows full-width (40 fields) AND most first
        # fields a clean 0/1
        import os
        forced = os.environ.get("PADDLE_TPU_CRITEO_TEST_LABELED")
        if forced is not None:
            return _real_creator(_TEST_FILE,
                                 has_label=forced == "1")
        path = common.data_path("criteo", _TEST_FILE)
        votes_01, votes_full, seen = 0, 0, 0
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                parts = line.rstrip("\n").split("\t")
                if len(parts) > NUM_DENSE + NUM_SPARSE:
                    votes_full += 1
                if parts[0].strip() in ("0", "1"):
                    votes_01 += 1
                seen += 1
                if seen >= 100:
                    break
        # BOTH majorities required: a single stray-tab or trimmed row
        # can't flip the verdict in either direction (trailing-trimmed
        # labeled files vote unlabeled — that's what the env override
        # above is for)
        has_label = (seen > 0 and votes_01 * 2 >= seen
                     and votes_full * 2 >= seen)
        return _real_creator(_TEST_FILE, has_label=has_label)
    return _creator(TEST_SIZE, 7_000_000)
