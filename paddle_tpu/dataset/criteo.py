"""Criteo-style CTR reader creators (reference: the dist_ctr test data
and models-repo criteo dataset: 13 dense + 26 sparse slots + click).
Synthetic, learnable, deterministic."""

from __future__ import annotations

import numpy as np

NUM_DENSE = 13
NUM_SPARSE = 26
SPARSE_DIM = 100000
TRAIN_SIZE = 4096
TEST_SIZE = 512


def _sample(idx):
    rs = np.random.RandomState(idx)
    dense = rs.rand(NUM_DENSE).astype(np.float32)
    sparse = rs.randint(0, SPARSE_DIM, size=NUM_SPARSE).astype(np.int64)
    hot = (sparse < SPARSE_DIM // 20).any()
    p = 0.15 + 0.5 * hot + 0.3 * (dense[0] > 0.5)
    label = np.int64(rs.rand() < p)
    return dense, sparse, label


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def train():
    return _creator(TRAIN_SIZE, 0)


def test():
    return _creator(TEST_SIZE, 7_000_000)
