"""PASCAL VOC2012 segmentation reader creators.

Reference: python/paddle/dataset/voc2012.py — train()/test()/val()
iterate the Segmentation image sets (train()=trainval, test()=train,
val()=val — the reference's own mapping); samples are (CHW float32
image, HW int32 segmentation label map with the 21 VOC classes + 255
ignore border).

Real data: drop ``VOCtrainval_11-May-2012.tar`` under
``DATA_HOME/voc2012/`` — JPEGImages/*.jpg decode to the CHW contract
and SegmentationClass/*.png palette indices become the label map
(reference voc2012.py:44-66). Synthetic fallback: rectangles of a
class painted on background with an ignore ring, exercising the same
shapes the segmentation models consume.
"""

from __future__ import annotations

import io
import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

N_CLASSES = 21
IGNORE = 255
TRAIN_SIZE = 512
TEST_SIZE = 128
_H = _W = 128

_ARCHIVE = "VOCtrainval_11-May-2012.tar"
_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _sample(idx):
    rng = np.random.RandomState(idx)
    img = rng.randint(0, 60, size=(3, _H, _W)).astype(np.float32)
    seg = np.zeros((_H, _W), np.int32)
    for _ in range(int(rng.randint(1, 4))):
        cls = int(rng.randint(1, N_CLASSES))
        h0, w0 = int(rng.randint(_H - 32)), int(rng.randint(_W - 32))
        h1 = h0 + int(rng.randint(16, 32))
        w1 = w0 + int(rng.randint(16, 32))
        seg[h0:h1, w0:w1] = cls
        seg[h0:h1, w0] = IGNORE    # thin ignore border, VOC-style
        seg[h0, w0:w1] = IGNORE
        img[cls % 3, h0:h1, w0:w1] += 120.0
    return img, seg


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def _real_creator(sub_name):
    def reader():
        from PIL import Image

        path = common.data_path("voc2012", _ARCHIVE)
        with tarfile.open(path) as tf:
            members = {m.name: m for m in tf.getmembers()}
            sets = tf.extractfile(members[_SET_FILE.format(sub_name)])
            for line in sets:
                name = line.decode().strip()
                if not name:
                    continue
                data = tf.extractfile(
                    members[_DATA_FILE.format(name)]).read()
                label = tf.extractfile(
                    members[_LABEL_FILE.format(name)]).read()
                img = np.asarray(Image.open(io.BytesIO(data))
                                 .convert("RGB"), np.float32)
                # palette png: pixel values ARE the class ids (and
                # 255 ignore) in P mode
                seg = np.asarray(Image.open(io.BytesIO(label)),
                                 np.int32)
                yield img.transpose(2, 0, 1), seg

    return reader


def _pick(sub_name, n, base):
    if common.have_file("voc2012", _ARCHIVE):
        return _real_creator(sub_name)
    return _creator(n, base)


def train():
    """trainval split (reference voc2012.py:70)."""
    return _pick("trainval", TRAIN_SIZE, 0)


def test():
    """'train' split (reference voc2012.py:77 — its test() reads the
    train image set)."""
    return _pick("train", TEST_SIZE, 15_000_000)


def val():
    return _pick("val", TEST_SIZE, 16_000_000)
