"""PASCAL VOC2012 segmentation reader creators.

Reference: python/paddle/dataset/voc2012.py — train()/test()/val()
yield (CHW float32 image, HW int32 segmentation label map with the
21 VOC classes + 255 ignore border). Synthetic fallback: rectangles
of a class painted on background with an ignore ring, exercising
the same shapes the segmentation models consume.
"""

from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "val"]

N_CLASSES = 21
IGNORE = 255
TRAIN_SIZE = 512
TEST_SIZE = 128
_H = _W = 128


def _sample(idx):
    rng = np.random.RandomState(idx)
    img = rng.randint(0, 60, size=(3, _H, _W)).astype(np.float32)
    seg = np.zeros((_H, _W), np.int32)
    for _ in range(int(rng.randint(1, 4))):
        cls = int(rng.randint(1, N_CLASSES))
        h0, w0 = int(rng.randint(_H - 32)), int(rng.randint(_W - 32))
        h1 = h0 + int(rng.randint(16, 32))
        w1 = w0 + int(rng.randint(16, 32))
        seg[h0:h1, w0:w1] = cls
        seg[h0:h1, w0] = IGNORE    # thin ignore border, VOC-style
        seg[h0, w0:w1] = IGNORE
        img[cls % 3, h0:h1, w0:w1] += 120.0
    return img, seg


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def train():
    return _creator(TRAIN_SIZE, 0)


def test():
    return _creator(TEST_SIZE, 15_000_000)


def val():
    return _creator(TEST_SIZE, 16_000_000)
