"""MovieLens-1M reader creators.

Reference: python/paddle/dataset/movielens.py — samples are
user.value() + movie.value() + [[rating]] i.e. (user_id, gender_id,
age_id, job_id, movie_id, category_ids, title_ids, [score]);
plus the MovieInfo/UserInfo metadata accessors (max_movie_id:193,
max_user_id:201, max_job_id:216, movie_categories:225,
get_movie_title_dict:178).

Real data: drop ``ml-1m.zip`` under ``DATA_HOME/movielens/`` and the
"::"-separated latin-1 ``movies.dat``/``users.dat``/``ratings.dat``
inside are parsed (reference movielens.py:107-160: title year "(1995)"
stripped by regex, categories split on "|", the np.random(test_ratio)
train/test split seeded per reader). Synthetic catalog otherwise.
"""

from __future__ import annotations

import re
import zipfile

import numpy as np

from . import common

__all__ = ["train", "test", "MovieInfo", "UserInfo", "max_movie_id",
           "max_user_id", "max_job_id", "movie_categories",
           "get_movie_title_dict", "movie_info", "user_info",
           "age_table"]

_N_MOVIES = 400
_N_USERS = 600
_N_CATEGORIES = 18
_TITLE_WORDS = 512
age_table = [1, 18, 25, 35, 45, 50, 56]

_ARCHIVE = "ml-1m.zip"


class MovieInfo:
    """Reference: movielens.py:53."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [movie_categories().index(c) for c in self.categories],
                [get_movie_title_dict()[w.lower()]
                 for w in self.title.split()]]

    def __repr__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)


class UserInfo:
    """Reference: movielens.py:80."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)


# --- real-data catalog (parsed once, cached) -------------------------------

_META = None  # {"movies": {id: MovieInfo}, "users": {id: UserInfo},
#               "categories": [..], "title_dict": {word: id}}


def _load_meta():
    """Parse movies.dat + users.dat (reference movielens.py:107-148)."""
    global _META
    if _META is not None:
        return _META
    path = common.data_path("movielens", _ARCHIVE)
    year_pat = re.compile(r"^(.*)\((\d+)\)$")
    movies, categories, title_words = {}, [], []
    cat_seen, word_seen = set(), set()
    users = {}
    with zipfile.ZipFile(path) as package:
        with package.open("ml-1m/movies.dat") as f:
            for line in f:
                line = line.decode("latin")
                movie_id, title, cats = line.strip().split("::")
                cats = cats.split("|")
                for c in cats:
                    if c not in cat_seen:
                        cat_seen.add(c)
                        categories.append(c)
                m = year_pat.match(title)
                if m:
                    title = m.group(1)
                movies[int(movie_id)] = MovieInfo(
                    index=movie_id, categories=cats, title=title)
                for w in title.split():
                    w = w.lower()
                    if w not in word_seen:
                        word_seen.add(w)
                        title_words.append(w)
        with package.open("ml-1m/users.dat") as f:
            for line in f:
                line = line.decode("latin")
                uid, gender, age, job, _zip = line.strip().split("::")
                users[int(uid)] = UserInfo(index=uid, gender=gender,
                                           age=age, job_id=job)
    _META = {"movies": movies, "users": users,
             "categories": categories,
             "title_dict": {w: i for i, w in enumerate(title_words)}}
    return _META


def _have_real():
    return common.have_file("movielens", _ARCHIVE)


def movie_categories():
    if _have_real():
        return _load_meta()["categories"]
    return ["cat%02d" % i for i in range(_N_CATEGORIES)]


def get_movie_title_dict():
    if _have_real():
        return _load_meta()["title_dict"]
    return {"w%d" % i: i for i in range(_TITLE_WORDS)}


def _movie(i):
    rng = np.random.RandomState(1000 + i)
    cats = [movie_categories()[c] for c in
            rng.choice(_N_CATEGORIES, size=int(rng.randint(1, 4)),
                       replace=False)]
    title = " ".join("w%d" % t for t in
                     rng.randint(0, _TITLE_WORDS,
                                 size=int(rng.randint(1, 6))))
    return MovieInfo(i, cats, title)


def _user(i):
    rng = np.random.RandomState(2000 + i)
    return UserInfo(i, "M" if rng.rand() < 0.5 else "F",
                    age_table[int(rng.randint(len(age_table)))],
                    int(rng.randint(21)))


def movie_info():
    if _have_real():
        return _load_meta()["movies"]
    return {i: _movie(i) for i in range(1, _N_MOVIES + 1)}


def user_info():
    if _have_real():
        return _load_meta()["users"]
    return {i: _user(i) for i in range(1, _N_USERS + 1)}


def max_movie_id():
    if _have_real():
        return max(_load_meta()["movies"])
    return _N_MOVIES


def max_user_id():
    if _have_real():
        return max(_load_meta()["users"])
    return _N_USERS


def max_job_id():
    if _have_real():
        return max(u.job_id for u in _load_meta()["users"].values())
    return 20


def _rating(u, m):
    rng = np.random.RandomState(u * 100003 + m)
    # taste model: users like movies whose id shares low bits
    base = 3.0 + ((u ^ m) % 5 - 2) * 0.7
    return float(np.clip(round(base + rng.randn() * 0.5), 1, 5))


def _real_reader(is_test, test_ratio=0.1, rand_seed=0):
    """Stream ratings.dat with the reference's np.random split
    (movielens.py:152-166)."""
    def reader():
        meta = _load_meta()
        # resolve .value() once per movie/user (a few thousand calls),
        # NOT once per rating line (a million) — value() walks the
        # category/title dicts each time
        movie_vals = {i: m.value() for i, m in meta["movies"].items()}
        user_vals = {i: u.value() for i, u in meta["users"].items()}
        path = common.data_path("movielens", _ARCHIVE)
        rng = np.random.RandomState(rand_seed)
        with zipfile.ZipFile(path) as package:
            with package.open("ml-1m/ratings.dat") as f:
                for line in f:
                    line = line.decode("latin")
                    if (rng.random_sample() < test_ratio) != bool(
                            is_test):
                        continue
                    uid, mov_id, rating, _ts = line.strip().split("::")
                    yield (user_vals[int(uid)]
                           + movie_vals[int(mov_id)]
                           + [[float(rating)]])

    return reader


def _reader(is_test, test_ratio=0.1, rand_seed=0):
    def reader():
        rng = np.random.RandomState(rand_seed)
        for u in range(1, _N_USERS + 1):
            n = int(np.random.RandomState(u).randint(5, 15))
            movies = np.random.RandomState(u + 7).randint(
                1, _N_MOVIES + 1, size=n)
            for m in movies:
                in_test = rng.rand() < test_ratio
                if in_test != bool(is_test):
                    continue
                yield _user(u).value() + _movie(int(m)).value() + \
                    [[_rating(u, int(m))]]

    return reader


def train():
    if _have_real():
        return _real_reader(is_test=False)
    return _reader(is_test=False)


def test():
    if _have_real():
        return _real_reader(is_test=True)
    return _reader(is_test=True)
