"""UCI housing reader creators (reference:
python/paddle/dataset/uci_housing.py — 13 float features, 1 float
target).

Real data: drop ``housing.data`` (whitespace-separated, 14 columns)
under ``DATA_HOME/uci_housing/`` and it is parsed with the reference's
normalization and 80/20 split (uci_housing.py:69-82: per-feature
(x - avg) / (max - min) over the WHOLE file, first 80% train).
Synthetic linear task with noise otherwise."""

from __future__ import annotations

import numpy as np

from . import common

_W = np.linspace(-1.0, 1.0, 13).astype(np.float32)
TRAIN_SIZE = 404
TEST_SIZE = 102

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD",
    "TAX", "PTRATIO", "B", "LSTAT",
]

_FILENAME = "housing.data"


def _sample(idx):
    rng = np.random.RandomState(idx)
    x = rng.rand(13).astype(np.float32)
    y = np.float32(x @ _W + 0.05 * rng.randn())
    return x, np.array([y], np.float32)


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def _load_real(ratio=0.8, feature_num=14):
    data = np.fromfile(common.data_path("uci_housing", _FILENAME),
                       sep=" ")
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.sum(axis=0) / data.shape[0]
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def _real_creator(is_test):
    def reader():
        train_rows, test_rows = _load_real()
        for d in (test_rows if is_test else train_rows):
            yield d[:-1].astype(np.float32), d[-1:].astype(np.float32)

    return reader


def train():
    if common.have_file("uci_housing", _FILENAME):
        return _real_creator(is_test=False)
    return _creator(TRAIN_SIZE, 0)


def test():
    if common.have_file("uci_housing", _FILENAME):
        return _real_creator(is_test=True)
    return _creator(TEST_SIZE, 1_000_000)
