"""UCI housing reader creators (reference:
python/paddle/dataset/uci_housing.py — 13 float features, 1 float
target). Synthetic linear task with noise."""

from __future__ import annotations

import numpy as np

_W = np.linspace(-1.0, 1.0, 13).astype(np.float32)
TRAIN_SIZE = 404
TEST_SIZE = 102


def _sample(idx):
    rng = np.random.RandomState(idx)
    x = rng.rand(13).astype(np.float32)
    y = np.float32(x @ _W + 0.05 * rng.randn())
    return x, np.array([y], np.float32)


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def train():
    return _creator(TRAIN_SIZE, 0)


def test():
    return _creator(TEST_SIZE, 1_000_000)
