"""WMT16 EN<->DE reader creators.

Reference: python/paddle/dataset/wmt16.py — train/test/validation
(src_dict_size, trg_dict_size, src_lang) yield (src_ids, trg_ids,
trg_ids_next); get_dict(lang, dict_size) returns the vocab.

Real data: drop ``wmt16.tar.gz`` under ``DATA_HOME/wmt16/`` — a tar
with ``wmt16/train`` / ``wmt16/test`` / ``wmt16/val`` members of
"en sentence\\tde sentence" lines. Vocabularies are built from the
train corpus by frequency with <s>/<e>/<unk> as ids 0/1/2 and cached
to ``DATA_HOME/wmt16/{lang}_{size}.dict`` (reference wmt16.py:62-100),
then both sides are id-mapped with <s>/<e> wrapping
(wmt16.py:110-145). Synthetic fallback with the same id conventions
otherwise.
"""

from __future__ import annotations

import os
import tarfile

import numpy as np

from . import common
from . import wmt14

__all__ = ["train", "test", "validation", "get_dict"]

TRAIN_SIZE = 2048
TEST_SIZE = 256
VALID_SIZE = 256

_S, _E, _U = "<s>", "<e>", "<unk>"
_ARCHIVE = "wmt16.tar.gz"


def _creator(n, base, src_size, trg_size):
    def reader():
        for i in range(n):
            rng = np.random.RandomState(base + i)
            ln = int(rng.randint(4, 30))
            src = rng.randint(3, src_size, size=ln).tolist()
            trg = [3 + (t * 13 + 7) % (trg_size - 3) for t in src]
            yield src, [wmt14.START] + trg, trg + [wmt14.END]

    return reader


def _have_real():
    return common.have_file("wmt16", _ARCHIVE)


def _build_dict(dict_size, save_path, lang):
    """train-corpus frequency vocab, <s>/<e>/<unk> first (reference
    wmt16.py:62-83)."""
    freq = {}
    with tarfile.open(common.data_path("wmt16", _ARCHIVE),
                      mode="r") as f:
        for line in f.extractfile("wmt16/train"):
            parts = line.decode("utf-8", "replace").strip().split("\t")
            if len(parts) != 2:
                continue
            sen = parts[0] if lang == "en" else parts[1]
            for w in sen.split():
                freq[w] = freq.get(w, 0) + 1
    with open(save_path, "w", encoding="utf-8") as fout:
        fout.write("%s\n%s\n%s\n" % (_S, _E, _U))
        for idx, (word, _c) in enumerate(
                sorted(freq.items(), key=lambda x: x[1], reverse=True)):
            if idx + 3 == dict_size:
                break
            fout.write(word + "\n")


def _load_dict(dict_size, lang, reverse=False):
    dict_path = common.data_path("wmt16",
                                 "%s_%d.dict" % (lang, dict_size))
    if (not os.path.exists(dict_path)
            or len(open(dict_path, "rb").readlines()) != dict_size):
        _build_dict(dict_size, dict_path, lang)
    word_dict = {}
    with open(dict_path, encoding="utf-8") as f:
        for idx, line in enumerate(f):
            if reverse:
                word_dict[idx] = line.strip()
            else:
                word_dict[line.strip()] = idx
    return word_dict


def _real_creator(file_name, src_dict_size, trg_dict_size, src_lang):
    def reader():
        src_dict = _load_dict(src_dict_size, src_lang)
        trg_dict = _load_dict(trg_dict_size,
                              "de" if src_lang == "en" else "en")
        start_id, end_id, unk_id = (src_dict[_S], src_dict[_E],
                                    src_dict[_U])
        src_col = 0 if src_lang == "en" else 1
        with tarfile.open(common.data_path("wmt16", _ARCHIVE),
                          mode="r") as f:
            for line in f.extractfile(file_name):
                parts = line.decode("utf-8", "replace").strip() \
                    .split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + [
                    src_dict.get(w, unk_id)
                    for w in parts[src_col].split()] + [end_id]
                trg_ids = [trg_dict.get(w, unk_id)
                           for w in parts[1 - src_col].split()]
                yield (src_ids, [start_id] + trg_ids,
                       trg_ids + [end_id])

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    if _have_real():
        return _real_creator("wmt16/train", src_dict_size,
                             trg_dict_size, src_lang)
    return _creator(TRAIN_SIZE, 0, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    if _have_real():
        return _real_creator("wmt16/test", src_dict_size,
                             trg_dict_size, src_lang)
    return _creator(TEST_SIZE, 7_000_000, src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    if _have_real():
        return _real_creator("wmt16/val", src_dict_size,
                             trg_dict_size, src_lang)
    return _creator(VALID_SIZE, 8_000_000, src_dict_size,
                    trg_dict_size)


def get_dict(lang, dict_size, reverse=False):
    if _have_real():
        return _load_dict(dict_size, lang, reverse)
    words = [_S, _E, _U] + [
        "%s%d" % (lang, i) for i in range(3, dict_size)]
    if reverse:
        return {i: w for i, w in enumerate(words)}
    return {w: i for i, w in enumerate(words)}
