"""WMT16 EN<->DE reader creators.

Reference: python/paddle/dataset/wmt16.py — train/test/validation
(src_dict_size, trg_dict_size, src_lang) yield (src_ids, trg_ids,
trg_ids_next); get_dict(lang, dict_size) returns the vocab. Same
synthetic-fallback policy as wmt14.
"""

from __future__ import annotations

import numpy as np

from . import wmt14

__all__ = ["train", "test", "validation", "get_dict"]

TRAIN_SIZE = 2048
TEST_SIZE = 256
VALID_SIZE = 256


def _creator(n, base, src_size, trg_size):
    def reader():
        for i in range(n):
            rng = np.random.RandomState(base + i)
            ln = int(rng.randint(4, 30))
            src = rng.randint(3, src_size, size=ln).tolist()
            trg = [3 + (t * 13 + 7) % (trg_size - 3) for t in src]
            yield src, [wmt14.START] + trg, trg + [wmt14.END]

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator(TRAIN_SIZE, 0, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator(TEST_SIZE, 7_000_000, src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator(VALID_SIZE, 8_000_000, src_dict_size,
                    trg_dict_size)


def get_dict(lang, dict_size, reverse=False):
    words = ["<s>", "<e>", "<unk>"] + [
        "%s%d" % (lang, i) for i in range(3, dict_size)]
    if reverse:
        return {i: w for i, w in enumerate(words)}
    return {w: i for i, w in enumerate(words)}
