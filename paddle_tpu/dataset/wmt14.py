"""WMT14 EN->FR reader creators.

Reference: python/paddle/dataset/wmt14.py — train(dict_size)/
test(dict_size) yield (src_ids, trg_ids, trg_ids_next) where trg_ids
is <s>-prefixed and trg_ids_next <e>-suffixed; get_dict(dict_size)
returns (src_dict, trg_dict).

Real data: drop ``wmt14.tgz`` under ``DATA_HOME/wmt14/`` — a tar with
``*src.dict`` / ``*trg.dict`` vocab members (one word per line, line
number = id) and ``train/train`` / ``test/test`` corpus members of
tab-separated "src sentence\\ttrg sentence" lines. It is parsed the
reference way (wmt14.py:56-115: first dict_size vocab lines, <s>/<e>
wrapping on the source words, >80-token pairs dropped). Synthetic
fallback: a deterministic parallel corpus with the same id
conventions (0=<s>, 1=<e>, 2=<unk>).
"""

from __future__ import annotations

import tarfile

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]

START = 0   # <s>
END = 1     # <e>
UNK = 2     # <unk>

_S, _E, _U = "<s>", "<e>", "<unk>"

TRAIN_SIZE = 2048
TEST_SIZE = 256

_ARCHIVE = "wmt14.tgz"


def _sample(idx, dict_size):
    rng = np.random.RandomState(idx)
    n = int(rng.randint(4, 30))
    src = rng.randint(3, dict_size, size=n).tolist()
    # translated sentence: deterministic per-token remap + length jitter
    trg = [3 + (t * 7 + 11) % (dict_size - 3) for t in src]
    if n > 5 and idx % 3 == 0:
        trg = trg[:-1]
    return src, [START] + trg, trg + [END]


def _creator(n, base, dict_size):
    def reader():
        for i in range(n):
            yield _sample(base + i, dict_size)

    return reader


def _have_real():
    return common.have_file("wmt14", _ARCHIVE)


def _read_to_dict(dict_size):
    """First ``dict_size`` vocab lines -> word:line_no (reference
    wmt14.py:56-79: exactly one member each ending src.dict /
    trg.dict)."""
    def to_dict(f):
        out = {}
        for i, line in enumerate(f):
            if i >= dict_size:
                break
            out[line.decode("utf-8", "replace").strip()] = i
        return out

    path = common.data_path("wmt14", _ARCHIVE)
    with tarfile.open(path, mode="r") as f:
        src_names = [m.name for m in f if m.name.endswith("src.dict")]
        trg_names = [m.name for m in f if m.name.endswith("trg.dict")]
        if len(src_names) != 1 or len(trg_names) != 1:
            raise ValueError(
                "wmt14 archive must contain exactly one src.dict and "
                "one trg.dict member (got %r, %r)"
                % (src_names, trg_names))
        return (to_dict(f.extractfile(src_names[0])),
                to_dict(f.extractfile(trg_names[0])))


def _real_creator(file_name, dict_size):
    def reader():
        src_dict, trg_dict = _read_to_dict(dict_size)
        path = common.data_path("wmt14", _ARCHIVE)
        with tarfile.open(path, mode="r") as f:
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode("utf-8", "replace").strip() \
                        .split("\t")
                    if len(parts) != 2:
                        continue
                    src_words = parts[0].split()
                    src_ids = [src_dict.get(w, UNK)
                               for w in [_S] + src_words + [_E]]
                    trg_words = parts[1].split()
                    trg_ids = [trg_dict.get(w, UNK) for w in trg_words]
                    # reference drops >80-token pairs (wmt14.py:107)
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    trg_next = trg_ids + [trg_dict[_E]]
                    trg_ids = [trg_dict[_S]] + trg_ids
                    yield src_ids, trg_ids, trg_next

    return reader


def train(dict_size):
    """Reference: wmt14.py:118."""
    if _have_real():
        return _real_creator("train/train", dict_size)
    return _creator(TRAIN_SIZE, 0, dict_size)


def test(dict_size):
    """Reference: wmt14.py:134."""
    if _have_real():
        return _real_creator("test/test", dict_size)
    return _creator(TEST_SIZE, 5_000_000, dict_size)


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict); id->word when ``reverse`` (reference:
    wmt14.py:156 — note the reference defaults reverse=True there)."""
    if _have_real():
        src, trg = _read_to_dict(dict_size)
        if reverse:
            return ({i: w for w, i in src.items()},
                    {i: w for w, i in trg.items()})
        return src, trg

    def one(prefix):
        words = [_S, _E, _U] + [
            "%s%d" % (prefix, i) for i in range(3, dict_size)]
        if reverse:
            return {i: w for i, w in enumerate(words)}
        return {w: i for i, w in enumerate(words)}

    return one("src"), one("trg")
