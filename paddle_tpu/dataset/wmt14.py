"""WMT14 EN->FR reader creators.

Reference: python/paddle/dataset/wmt14.py — train(dict_size)/
test(dict_size) yield (src_ids, trg_ids, trg_ids_next) where trg_ids
is <s>-prefixed and trg_ids_next <e>-suffixed; get_dict(dict_size)
returns (src_dict, trg_dict). Real data: drop the preprocessed
``wmt14/train.tgz``-style id files under DATA_HOME; otherwise a
deterministic synthetic parallel corpus with the same id conventions
(0=<s>, 1=<e>, 2=<unk>) is generated.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "get_dict"]

START = 0   # <s>
END = 1     # <e>
UNK = 2     # <unk>

TRAIN_SIZE = 2048
TEST_SIZE = 256


def _sample(idx, dict_size):
    rng = np.random.RandomState(idx)
    n = int(rng.randint(4, 30))
    src = rng.randint(3, dict_size, size=n).tolist()
    # translated sentence: deterministic per-token remap + length jitter
    trg = [3 + (t * 7 + 11) % (dict_size - 3) for t in src]
    if n > 5 and idx % 3 == 0:
        trg = trg[:-1]
    return src, [START] + trg, trg + [END]


def _creator(n, base, dict_size):
    def reader():
        for i in range(n):
            yield _sample(base + i, dict_size)

    return reader


def train(dict_size):
    """Reference: wmt14.py:118."""
    return _creator(TRAIN_SIZE, 0, dict_size)


def test(dict_size):
    """Reference: wmt14.py:134."""
    return _creator(TEST_SIZE, 5_000_000, dict_size)


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict); id->word when ``reverse`` (reference:
    wmt14.py:156 — note the reference defaults reverse=True there)."""
    def one(prefix):
        words = ["<s>", "<e>", "<unk>"] + [
            "%s%d" % (prefix, i) for i in range(3, dict_size)]
        if reverse:
            return {i: w for i, w in enumerate(words)}
        return {w: i for i, w in enumerate(words)}

    return one("src"), one("trg")
