"""CoNLL-2005 semantic-role-labeling reader creators.

Reference: python/paddle/dataset/conll05.py — test() yields
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark,
label_ids): per-token word ids, five predicate-context windows
broadcast over the sentence, predicate ids, a 0/1 predicate-adjacency
mark, and IOB label ids; get_dict() returns (word_dict, verb_dict,
label_dict).

Real data under ``DATA_HOME/conll05st/``: ``conll05st-tests.tar.gz``
(the words/props gz members, parsed with the reference's bracket->IOB
algorithm, conll05.py:76-147) plus ``wordDict.txt`` / ``verbDict.txt``
/ ``targetDict.txt`` (one entry per line; the label dict expands each
tag into B-/I- pairs with O last, conll05.py:49-65 — tags sorted here
for determinism where the reference iterates a set). Synthetic
sentences with the exact field conventions otherwise.
"""

from __future__ import annotations

import gzip
import tarfile

import numpy as np

from . import common

__all__ = ["get_dict", "get_embedding", "test"]

_WORDS = 4000
_VERBS = 200
_LABELS = ["B-A0", "I-A0", "B-A1", "I-A1", "B-V", "O"]
_TEST_SIZE = 512

UNK_IDX = 0

_MODULE = "conll05st"
_ARCHIVE = "conll05st-tests.tar.gz"
_WORDDICT = "wordDict.txt"
_VERBDICT = "verbDict.txt"
_TRGDICT = "targetDict.txt"
_WORDS_MEMBER = "conll05st-release/test.wsj/words/test.wsj.words.gz"
_PROPS_MEMBER = "conll05st-release/test.wsj/props/test.wsj.props.gz"


def _have_real():
    return all(common.have_file(_MODULE, f)
               for f in (_ARCHIVE, _WORDDICT, _VERBDICT, _TRGDICT))


def _load_dict(filename):
    d = {}
    with open(common.data_path(_MODULE, filename)) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _load_label_dict(filename):
    """targetDict lines like B-A0/I-A0 -> {B-tag, I-tag} id pairs with
    O last (reference conll05.py:49-65; tags sorted for
    determinism)."""
    tags = set()
    with open(common.data_path(_MODULE, filename)) as f:
        for line in f:
            line = line.strip()
            if line.startswith("B-") or line.startswith("I-"):
                tags.add(line[2:])
    d = {}
    for tag in sorted(tags):
        d["B-" + tag] = len(d)
        d["I-" + tag] = len(d)
    d["O"] = len(d)
    return d


def get_dict():
    if _have_real():
        return (_load_dict(_WORDDICT), _load_dict(_VERBDICT),
                _load_label_dict(_TRGDICT))
    word_dict = {"w%d" % i: i for i in range(_WORDS)}
    verb_dict = {"v%d" % i: i for i in range(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """The pretrained emb table as a float32 ndarray — parsed from the
    real whitespace-float ``emb`` file when present under DATA_HOME
    (one row per word; the reference returns the file path and leaves
    loading to the caller, conll05.py:221), else a deterministic
    stand-in. One return type either way."""
    if common.have_file(_MODULE, "emb"):
        return np.loadtxt(common.data_path(_MODULE, "emb"),
                          dtype=np.float32)
    rng = np.random.RandomState(0)
    return rng.randn(_WORDS, 32).astype(np.float32)


def _bracket_to_iob(lbl):
    """One predicate column ('(A0*', '*', '*)', '(V*)'...) -> IOB
    sequence (reference conll05.py:107-133)."""
    cur_tag, in_bracket = "O", False
    out = []
    for l in lbl:
        if l == "*" and not in_bracket:
            out.append("O")
        elif l == "*" and in_bracket:
            out.append("I-" + cur_tag)
        elif l == "*)":
            out.append("I-" + cur_tag)
            in_bracket = False
        elif "(" in l and ")" in l:
            cur_tag = l[1:l.find("*")]
            out.append("B-" + cur_tag)
            in_bracket = False
        elif "(" in l:
            cur_tag = l[1:l.find("*")]
            out.append("B-" + cur_tag)
            in_bracket = True
        else:
            raise RuntimeError("Unexpected SRL label: %s" % l)
    return out


def _corpus_reader():
    """Yield (sentence_words, predicate, iob_labels) per predicate per
    sentence from the words/props gz pair (reference
    conll05.py:76-147: words one per line, props one field-row per
    token with the lemma column first, blank lines separate
    sentences)."""
    path = common.data_path(_MODULE, _ARCHIVE)
    with tarfile.open(path) as tf:
        wf = tf.extractfile(_WORDS_MEMBER)
        pf = tf.extractfile(_PROPS_MEMBER)
        with gzip.GzipFile(fileobj=wf) as words_file, \
                gzip.GzipFile(fileobj=pf) as props_file:
            sentence, rows = [], []
            for word, props in zip(words_file, props_file):
                word = word.decode("utf-8", "replace").strip()
                fields = props.decode("utf-8", "replace").strip() \
                    .split()
                if fields:
                    sentence.append(word)
                    rows.append(fields)
                    continue
                # end of sentence: column 0 = lemmas, column i>0 =
                # bracket labels of predicate i
                if rows:
                    cols = [[r[i] for r in rows]
                            for i in range(len(rows[0]))]
                    verbs = [x for x in cols[0] if x != "-"]
                    for i, lbl in enumerate(cols[1:]):
                        yield sentence, verbs[i], _bracket_to_iob(lbl)
                sentence, rows = [], []


def _fields(sentence, predicate, labels, word_dict, predicate_dict,
            label_dict):
    """Assemble the 9-field sample (reference conll05.py:150-204)."""
    n = len(sentence)
    verb_index = labels.index("B-V")
    mark = [0] * n

    def ctx(off, default):
        p = verb_index + off
        if 0 <= p < n:
            mark[p] = 1
            return sentence[p]
        return default

    ctx_n2 = ctx(-2, "bos")
    ctx_n1 = ctx(-1, "bos")
    ctx_0 = ctx(0, sentence[verb_index])
    ctx_p1 = ctx(1, "eos")
    ctx_p2 = ctx(2, "eos")

    def widx(w):
        return word_dict.get(w, UNK_IDX)

    # fail loudly on dict gaps: the reference's .get() would embed
    # None ids that crash far from the cause (conll05.py:197-198)
    if predicate not in predicate_dict:
        raise KeyError("predicate %r not in verbDict" % predicate)
    missing = [l for l in labels if l not in label_dict]
    if missing:
        raise KeyError("labels %r not in targetDict" % missing[:5])

    return ([widx(w) for w in sentence],
            [widx(ctx_n2)] * n, [widx(ctx_n1)] * n, [widx(ctx_0)] * n,
            [widx(ctx_p1)] * n, [widx(ctx_p2)] * n,
            [predicate_dict[predicate]] * n, mark,
            [label_dict[l] for l in labels])


def _real_creator():
    def reader():
        word_dict, verb_dict, label_dict = get_dict()
        for sentence, predicate, labels in _corpus_reader():
            yield _fields(sentence, predicate, labels, word_dict,
                          verb_dict, label_dict)

    return reader


def _sample(idx):
    rng = np.random.RandomState(idx)
    n = int(rng.randint(5, 25))
    words = rng.randint(0, _WORDS, size=n)
    pred_pos = int(rng.randint(n))
    verb = int(rng.randint(_VERBS))

    def ctx(off):
        p = min(max(pred_pos + off, 0), n - 1)
        return [int(words[p])] * n

    mark = [1 if abs(i - pred_pos) <= 1 else 0 for i in range(n)]
    labels = []
    i = 0
    while i < n:
        if i == pred_pos:
            labels.append(_LABELS.index("B-V"))
            i += 1
        elif rng.rand() < 0.3 and i + 1 < n:
            role = "A0" if rng.rand() < 0.5 else "A1"
            labels.append(_LABELS.index("B-" + role))
            labels.append(_LABELS.index("I-" + role))
            i += 2
        else:
            labels.append(_LABELS.index("O"))
            i += 1
    labels = labels[:n]
    return (words.astype(np.int64).tolist(), ctx(-2), ctx(-1), ctx(0),
            ctx(1), ctx(2), [verb] * n, mark, labels)


def test():
    if _have_real():
        return _real_creator()

    def reader():
        for i in range(_TEST_SIZE):
            yield _sample(11_000_000 + i)

    return reader
