"""CoNLL-2005 semantic-role-labeling reader creators.

Reference: python/paddle/dataset/conll05.py — test() yields
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark,
label_ids): per-token word ids, five predicate-context windows
broadcast over the sentence, predicate ids, a 0/1 predicate-adjacency
mark, and IOB label ids; get_dict() returns (word_dict, verb_dict,
label_dict). Synthetic sentences follow the exact field conventions.
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_dict", "get_embedding", "test"]

_WORDS = 4000
_VERBS = 200
_LABELS = ["B-A0", "I-A0", "B-A1", "I-A1", "B-V", "O"]
_TEST_SIZE = 512


def get_dict():
    word_dict = {"w%d" % i: i for i in range(_WORDS)}
    verb_dict = {"v%d" % i: i for i in range(_VERBS)}
    label_dict = {l: i for i, l in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic stand-in for the pretrained emb table the
    reference downloads (conll05.py get_embedding)."""
    rng = np.random.RandomState(0)
    return rng.randn(_WORDS, 32).astype(np.float32)


def _sample(idx):
    rng = np.random.RandomState(idx)
    n = int(rng.randint(5, 25))
    words = rng.randint(0, _WORDS, size=n)
    pred_pos = int(rng.randint(n))
    verb = int(rng.randint(_VERBS))

    def ctx(off):
        p = min(max(pred_pos + off, 0), n - 1)
        return [int(words[p])] * n

    mark = [1 if abs(i - pred_pos) <= 1 else 0 for i in range(n)]
    labels = []
    i = 0
    while i < n:
        if i == pred_pos:
            labels.append(_LABELS.index("B-V"))
            i += 1
        elif rng.rand() < 0.3 and i + 1 < n:
            role = "A0" if rng.rand() < 0.5 else "A1"
            labels.append(_LABELS.index("B-" + role))
            labels.append(_LABELS.index("I-" + role))
            i += 2
        else:
            labels.append(_LABELS.index("O"))
            i += 1
    labels = labels[:n]
    return (words.astype(np.int64).tolist(), ctx(-2), ctx(-1), ctx(0),
            ctx(1), ctx(2), [verb] * n, mark, labels)


def test():
    def reader():
        for i in range(_TEST_SIZE):
            yield _sample(11_000_000 + i)

    return reader
