"""Dataset zoo (reference: python/paddle/dataset/ — mnist, cifar,
uci_housing, imdb, movielens... with auto-download).

This environment has zero egress, so each dataset is a *deterministic
synthetic generator* with the reference's exact sample shapes/dtypes and
reader-creator API (``train()``/``test()`` return zero-arg callables
yielding samples). Real data can be dropped into
``PADDLE_TPU_DATA_HOME`` using the same file layout to override."""

from . import cifar  # noqa: F401
from . import common  # noqa: F401
from . import conll05  # noqa: F401
from . import criteo  # noqa: F401
from . import flowers  # noqa: F401
from . import image  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import mnist  # noqa: F401
from . import movielens  # noqa: F401
from . import mq2007  # noqa: F401
from . import sentiment  # noqa: F401
from . import uci_housing  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
