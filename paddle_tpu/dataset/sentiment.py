"""Movie-review sentiment reader creators.

Reference: python/paddle/dataset/sentiment.py (NLTK movie_reviews:
get_word_dict():64 sorted by frequency, train()/test() yield
(word-id list, 0/1 label — neg=0) with an 80/20 split over the
neg/pos-interleaved file order — both the synthetic and real paths
use the same 80/20 convention).

Real data: drop the NLTK corpus at
``DATA_HOME/corpora/movie_reviews/{neg,pos}/*.txt`` (the layout
``nltk.download('movie_reviews')`` produces) and the plain-text
reviews are tokenized and id-mapped (reference sentiment.py:56-106).
Synthetic fallback: polarity carried by disjoint token ranges with
shared filler words.
"""

from __future__ import annotations

import glob
import os
import re

import numpy as np

from . import common

__all__ = ["get_word_dict", "train", "test"]

_VOCAB = 1000
_N_DOCS = 1024
NUM_TRAINING_INSTANCES = int(_N_DOCS * 0.8)
NUM_TOTAL_INSTANCES = _N_DOCS

_CORPUS_DIR = os.path.join("corpora", "movie_reviews")
_TOKEN_RE = re.compile(r"\w+|[^\w\s]")


def _corpus_root():
    return os.path.join(common.DATA_HOME, _CORPUS_DIR)


def _have_real():
    root = _corpus_root()
    return (os.path.isdir(os.path.join(root, "neg"))
            and os.path.isdir(os.path.join(root, "pos")))


def _files(category):
    return sorted(glob.glob(os.path.join(_corpus_root(), category,
                                         "*.txt")))


def _tokens(path):
    with open(path, encoding="utf-8", errors="replace") as f:
        return [t.lower() for t in _TOKEN_RE.findall(f.read())]


def _interleaved_files():
    """neg/pos alternating, as the reference's sort_files()
    (sentiment.py:77) cross-reads the classes; unpaired leftovers of
    the larger class follow at the end (zip-truncation would silently
    drop documents)."""
    neg, pos = _files("neg"), _files("pos")
    out = []
    for n_, p_ in zip(neg, pos):
        out.append((n_, 0))
        out.append((p_, 1))
    k = min(len(neg), len(pos))
    out += [(f, 0) for f in neg[k:]] + [(f, 1) for f in pos[k:]]
    return out


_DICT_CACHE = {}  # corpus root -> word dict


def get_word_dict():
    """word -> id, most frequent first (reference: sentiment.py:56).
    Cached per corpus root: rebuilding means re-tokenizing the whole
    corpus."""
    if not _have_real():
        return {"w%d" % i: i for i in range(_VOCAB)}
    root = _corpus_root()
    cached = _DICT_CACHE.get(root)
    if cached is not None:
        return cached
    freq = {}
    for path, _lbl in _interleaved_files():
        for w in _tokens(path):
            freq[w] = freq.get(w, 0) + 1
    words = sorted(freq, key=lambda w: (-freq[w], w))
    out = {w: i for i, w in enumerate(words)}
    _DICT_CACHE[root] = out
    return out


def _doc(idx):
    rng = np.random.RandomState(idx)
    label = idx % 2
    n = int(rng.randint(10, 80))
    filler = rng.randint(0, _VOCAB // 2, size=n)
    polar_lo = _VOCAB // 2 if label else 3 * _VOCAB // 4
    polar = rng.randint(polar_lo, polar_lo + _VOCAB // 4,
                        size=max(2, n // 4))
    ids = np.concatenate([filler, polar])
    rng.shuffle(ids)
    return ids.astype(np.int64).tolist(), np.int64(label)


def _creator(lo, hi):
    def reader():
        for i in range(lo, hi):
            yield _doc(i)

    return reader


def _real_creator(take_train):
    def reader():
        word_ids = get_word_dict()
        docs = _interleaved_files()
        split = int(len(docs) * 0.8)  # reference: 1600 of 2000
        part = docs[:split] if take_train else docs[split:]
        for path, label in part:
            ids = [word_ids[w] for w in _tokens(path)
                   if w in word_ids]
            yield ids, np.int64(label)

    return reader


def train():
    if _have_real():
        return _real_creator(take_train=True)
    return _creator(0, NUM_TRAINING_INSTANCES)


def test():
    if _have_real():
        return _real_creator(take_train=False)
    return _creator(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES)
