"""Movie-review sentiment reader creators.

Reference: python/paddle/dataset/sentiment.py (NLTK movie_reviews:
get_word_dict():64 sorted by frequency, train()/test() yield
(word-id list, 0/1 label) with a 90/10 split). Synthetic: polarity
carried by disjoint token ranges with shared filler words.
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_word_dict", "train", "test"]

_VOCAB = 1000
_N_DOCS = 1024
NUM_TRAINING_INSTANCES = int(_N_DOCS * 0.9)
NUM_TOTAL_INSTANCES = _N_DOCS


def get_word_dict():
    """word -> id, most frequent first (reference: sentiment.py:64)."""
    return {"w%d" % i: i for i in range(_VOCAB)}


def _doc(idx):
    rng = np.random.RandomState(idx)
    label = idx % 2
    n = int(rng.randint(10, 80))
    filler = rng.randint(0, _VOCAB // 2, size=n)
    polar_lo = _VOCAB // 2 if label else 3 * _VOCAB // 4
    polar = rng.randint(polar_lo, polar_lo + _VOCAB // 4,
                        size=max(2, n // 4))
    ids = np.concatenate([filler, polar])
    rng.shuffle(ids)
    return ids.astype(np.int64).tolist(), np.int64(label)


def _creator(lo, hi):
    def reader():
        for i in range(lo, hi):
            yield _doc(i)

    return reader


def train():
    return _creator(0, NUM_TRAINING_INSTANCES)


def test():
    return _creator(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES)
