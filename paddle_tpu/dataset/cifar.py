"""CIFAR reader creators (reference: python/paddle/dataset/cifar.py —
train10()/test10() yield (3072-float32 in [0,1], int label)).

Real data: drop ``cifar-10-python.tar.gz`` / ``cifar-100-python.tar.gz``
under ``DATA_HOME/cifar/`` and the pickled batches inside are parsed
(reference: cifar.py:48-74 — members matched by substring, ``data`` +
``labels``/``fine_labels`` keys, values scaled by 1/255). Synthetic
fallback otherwise."""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from . import common

TRAIN_SIZE = 4096
TEST_SIZE = 512

_CIFAR10 = "cifar-10-python.tar.gz"
_CIFAR100 = "cifar-100-python.tar.gz"


def _sample(idx, classes):
    rng = np.random.RandomState(idx)
    label = idx % classes
    img = rng.rand(3, 32, 32).astype(np.float32) * 0.2
    img[label % 3, (label * 3) % 32:(label * 3) % 32 + 4, :] += 0.8
    return img.reshape(-1), np.int64(label)


def _creator(n, base, classes):
    def reader():
        for i in range(n):
            yield _sample(base + i, classes)

    return reader


def _real_creator(archive, sub_name):
    """Parse the pickled python-version batches (reference
    cifar.py:48-74: members whose name contains ``sub_name``; labels
    under ``labels`` (cifar10) or ``fine_labels`` (cifar100))."""
    def reader():
        path = common.data_path("cifar", archive)
        with tarfile.open(path, mode="r") as f:
            names = sorted(m.name for m in f if sub_name in m.name)
            for name in names:
                batch = pickle.load(f.extractfile(name),
                                    encoding="bytes")
                data = batch[b"data"]
                labels = batch.get(b"labels",
                                   batch.get(b"fine_labels"))
                if labels is None:
                    raise ValueError("no labels in cifar batch %r"
                                     % name)
                for sample, label in zip(data, labels):
                    yield ((np.asarray(sample) / 255.0)
                           .astype(np.float32), int(label))

    return reader


def _pick(archive, sub_name, n, base, classes):
    if common.have_file("cifar", archive):
        return _real_creator(archive, sub_name)
    return _creator(n, base, classes)


def train10():
    return _pick(_CIFAR10, "data_batch", TRAIN_SIZE, 0, 10)


def test10():
    return _pick(_CIFAR10, "test_batch", TEST_SIZE, 5_000_000, 10)


def train100():
    return _pick(_CIFAR100, "train", TRAIN_SIZE, 0, 100)


def test100():
    return _pick(_CIFAR100, "test", TEST_SIZE, 5_000_000, 100)
