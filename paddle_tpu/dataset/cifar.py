"""CIFAR reader creators (reference: python/paddle/dataset/cifar.py —
train10()/test10() yield (3072-float32 in [0,1], int label))."""

from __future__ import annotations

import numpy as np

TRAIN_SIZE = 4096
TEST_SIZE = 512


def _sample(idx, classes):
    rng = np.random.RandomState(idx)
    label = idx % classes
    img = rng.rand(3, 32, 32).astype(np.float32) * 0.2
    img[label % 3, (label * 3) % 32:(label * 3) % 32 + 4, :] += 0.8
    return img.reshape(-1), np.int64(label)


def _creator(n, base, classes):
    def reader():
        for i in range(n):
            yield _sample(base + i, classes)

    return reader


def train10():
    return _creator(TRAIN_SIZE, 0, 10)


def test10():
    return _creator(TEST_SIZE, 5_000_000, 10)


def train100():
    return _creator(TRAIN_SIZE, 0, 100)


def test100():
    return _creator(TEST_SIZE, 5_000_000, 100)
