"""MNIST reader creators (reference: python/paddle/dataset/mnist.py —
train()/test() yield (784-float32 in [-1,1], int64 label)).

Real data: drop the four idx-format gzip files
(``train-images-idx3-ubyte.gz``/``train-labels-idx1-ubyte.gz`` and the
``t10k-`` pair) under ``DATA_HOME/mnist/`` and they are parsed
(reference: mnist.py:39-84 reads the same magic-numbered idx streams).
Synthetic fallback otherwise: class-conditional separable images so
models actually learn; deterministic per index."""

from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common

TRAIN_SIZE = 8192
TEST_SIZE = 1024

_TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
_TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
_TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
_TEST_LABELS = "t10k-labels-idx1-ubyte.gz"


def _sample(idx):
    rng = np.random.RandomState(idx)
    label = idx % 10
    img = rng.rand(784).astype(np.float32) * 0.2 - 1.0
    img[label * 78:(label + 1) * 78] += 1.2
    return img, np.int64(label)


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def _parse_idx(images_gz, labels_gz):
    """Parse the classic idx3/idx1 gzip pair (reference mnist.py:44-75
    reads the same header: magic, count, rows, cols big-endian)."""
    with gzip.open(common.data_path("mnist", labels_gz), "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError("bad idx1 magic %d in %s" % (magic, labels_gz))
        labels = np.frombuffer(f.read(n), dtype=np.uint8)
    with gzip.open(common.data_path("mnist", images_gz), "rb") as f:
        magic, n2, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError("bad idx3 magic %d in %s" % (magic, images_gz))
        images = np.frombuffer(f.read(n2 * rows * cols), dtype=np.uint8)
        images = images.reshape(n2, rows * cols)
    if n != n2:
        raise ValueError("mnist image/label count mismatch: %d vs %d"
                         % (n2, n))
    return images, labels


def _real_creator(images_gz, labels_gz):
    def reader():
        images, labels = _parse_idx(images_gz, labels_gz)
        # reference normalization: [0,255] -> [-1,1] (mnist.py:66)
        for img, label in zip(images, labels):
            yield (img.astype(np.float32) / 255.0 * 2.0 - 1.0,
                   np.int64(label))

    return reader


def _have_real(images_gz, labels_gz):
    return (common.have_file("mnist", images_gz)
            and common.have_file("mnist", labels_gz))


def train():
    if _have_real(_TRAIN_IMAGES, _TRAIN_LABELS):
        return _real_creator(_TRAIN_IMAGES, _TRAIN_LABELS)
    return _creator(TRAIN_SIZE, 0)


def test():
    if _have_real(_TEST_IMAGES, _TEST_LABELS):
        return _real_creator(_TEST_IMAGES, _TEST_LABELS)
    return _creator(TEST_SIZE, 10_000_000)
