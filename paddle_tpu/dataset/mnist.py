"""MNIST reader creators (reference: python/paddle/dataset/mnist.py —
train()/test() yield (784-float32 in [-1,1], int64 label)).

Synthetic fallback: class-conditional separable images so models
actually learn; deterministic per index."""

from __future__ import annotations

import numpy as np

TRAIN_SIZE = 8192
TEST_SIZE = 1024


def _sample(idx):
    rng = np.random.RandomState(idx)
    label = idx % 10
    img = rng.rand(784).astype(np.float32) * 0.2 - 1.0
    img[label * 78:(label + 1) * 78] += 1.2
    return img, np.int64(label)


def _creator(n, base):
    def reader():
        for i in range(n):
            yield _sample(base + i)

    return reader


def train():
    return _creator(TRAIN_SIZE, 0)


def test():
    return _creator(TEST_SIZE, 10_000_000)
