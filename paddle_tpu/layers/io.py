"""Feed-variable declaration (reference: python/paddle/fluid/layers/io.py
``data``). The reader-op machinery (create_py_reader_op etc.) is replaced
by the host-side pipeline in paddle_tpu.reader (async prefetch + device
infeed), so ``data`` only declares a feed slot."""

from __future__ import annotations

from ..layer_helper import LayerHelper


def data(name, shape, append_batch_size=True, dtype="float32",
         lod_level=0, type=None, stop_gradient=True):
    """Declare a feed variable. ``append_batch_size`` prepends -1 like the
    reference; -1 dims bind at trace time from the actual feed."""
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return helper.main_program.global_block().create_var(
        name=name, shape=tuple(shape), dtype=dtype, is_data=True,
        stop_gradient=stop_gradient, lod_level=lod_level)
