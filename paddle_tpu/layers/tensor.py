"""Tensor creation / manipulation layers (reference:
python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

import numpy as np

from .. import framework
from ..framework import Variable, convert_dtype
from ..layer_helper import LayerHelper


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.main_program.global_block().create_var(
        name=name or helper.name, dtype=dtype, shape=(),
        persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Reference: tensor.py create_global_var — persistable var + startup
    fill."""
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(shape=tuple(shape), dtype=dtype,
                                        persistable=persistable,
                                        name=name)
    sblock = helper.startup_program.global_block()
    sv = sblock.create_var(name=var.name, shape=tuple(shape), dtype=dtype,
                           persistable=persistable, stop_gradient=True)
    sblock.append_op(type="fill_constant", outputs={"Out": [sv]},
                     attrs={"shape": tuple(shape), "dtype": dtype,
                            "value": float(value)})
    return var


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    return helper.create_parameter(attr or name, shape, dtype, is_bias,
                                   default_initializer)


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="cast", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    from . import nn
    return nn.concat(input, axis, name)


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(input.dtype))
        helper.append_op(type="assign_numpy_value",
                         outputs={"Out": [output]},
                         attrs={"_value": input,
                                "dtype": str(input.dtype)})
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="assign", inputs={"X": [input]},
                     outputs={"Out": [output]})
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype, stop_gradient=True)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": tuple(shape), "dtype": dtype,
                            "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(
        convert_dtype(dtype), stop_gradient=True)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"shape": tuple(shape),
                            "dtype": convert_dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def _fill_like(x, out, value, helper_name):
    helper = LayerHelper(helper_name)
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="fill_any_like", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"value": value})
    return out


def ones_like(x, out=None):
    return _fill_like(x, out, 1.0, "ones_like")


def zeros_like(x, out=None):
    return _fill_like(x, out, 0.0, "zeros_like")


def range(start, end, step, dtype):
    helper = LayerHelper("range")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    helper.append_op(type="range", outputs={"Out": [out]},
                     attrs={"start": start, "end": end, "step": step,
                            "dtype": dtype})
    return out


def linspace(start, stop, num, dtype):
    helper = LayerHelper("linspace")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    helper.append_op(type="linspace", outputs={"Out": [out]},
                     attrs={"start": start, "stop": stop, "num": num,
                            "dtype": dtype})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op(type="diag", inputs={"Diagonal": [diagonal]},
                     outputs={"Out": [out]})
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    helper.append_op(type="eye", outputs={"Out": [out]},
                     attrs={"num_rows": num_rows,
                            "num_columns": num_columns, "dtype": dtype})
    return out


_INT_MAX = 2 ** 31 - 1  # "to the end" sentinel, as fluid's slice uses


def _getitem(var, item):
    """Variable.__getitem__ -> slice/strided_slice ops (math_op_patch
    parity). Negative indices and steps are supported; `x[-1]` uses the
    INT_MAX end sentinel so it works for dynamic (-1) leading dims."""
    from . import nn
    from ..layer_helper import LayerHelper
    if not isinstance(item, tuple):
        item = (item,)
    axes, starts, ends, strides, squeeze_axes = [], [], [], [], []
    for ax, it in enumerate(item):
        if isinstance(it, int):
            axes.append(ax)
            starts.append(it)
            ends.append(_INT_MAX if it == -1 else it + 1)
            strides.append(1)
            squeeze_axes.append(ax)
        elif isinstance(it, slice):
            step = it.step if it.step is not None else 1
            if it.start is None and it.stop is None and step == 1:
                continue
            axes.append(ax)
            if step > 0:
                starts.append(it.start if it.start is not None else 0)
                ends.append(it.stop if it.stop is not None else _INT_MAX)
            else:
                starts.append(it.start if it.start is not None
                              else _INT_MAX)
                ends.append(it.stop if it.stop is not None
                            else -_INT_MAX)
            strides.append(step)
        else:
            raise TypeError("unsupported index %r" % (it,))
    if not axes:
        out = var
    elif all(s == 1 for s in strides):
        out = nn.slice(var, axes, starts, ends)
    else:
        helper = LayerHelper("strided_slice")
        out = helper.create_variable_for_type_inference(var.dtype)
        helper.append_op(type="strided_slice", inputs={"X": [var]},
                         outputs={"Out": [out]},
                         attrs={"axes": tuple(axes),
                                "starts": tuple(starts),
                                "ends": tuple(ends),
                                "strides": tuple(strides)})
    if squeeze_axes:
        out = nn.squeeze(out, squeeze_axes)
    return out


def sum(x):
    """Alias of ``sums`` matching the reference export (layers.sum ->
    sum_op.cc: elementwise sum of a var list)."""
    return sums(x if isinstance(x, (list, tuple)) else [x])


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reverse", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"axis": tuple(axis)
                            if isinstance(axis, (list, tuple))
                            else (axis,)})
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pow", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"factor": factor})
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Reference: layers/tensor.py tensor_array_to_tensor ->
    tensor_array_to_tensor_op.cc. Returns (tensor, index)."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference(
        "int32", stop_gradient=True)
    helper.append_op(type="tensor_array_to_tensor",
                     inputs={"Array": [input]},
                     outputs={"Out": [out], "OutIndex": [idx]},
                     attrs={"axis": axis, "use_stack": use_stack})
    return out, idx


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 step counter incremented once per run
    (reference: layers/tensor.py autoincreased_step_counter — used by
    learning-rate schedules)."""
    from .. import framework
    helper = LayerHelper("step_counter")
    name = counter_name or "@STEP_COUNTER@"
    startup = helper.startup_program
    block = helper.main_program.global_block()
    if block.has_var(name):
        return block.var(name)
    counter = block.create_var(name=name, shape=(1,), dtype="int64",
                               persistable=True, stop_gradient=True)
    if startup is not None:
        sb = startup.global_block()
        sv = sb.create_var(name=name, shape=(1,), dtype="int64",
                           persistable=True, stop_gradient=True)
        sb.append_op(type="fill_constant", outputs={"Out": [sv]},
                     attrs={"shape": (1,), "dtype": "int64",
                            "value": float(begin - step)})
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]},
                     attrs={"step": float(step)})
    return counter
