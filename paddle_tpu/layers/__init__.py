"""fluid.layers-equivalent namespace: every public layer in one place
(reference: python/paddle/fluid/layers/__init__.py)."""

from . import math_op_patch  # noqa: F401  (registers Variable operators)
from .control_flow import (DynamicRNN, IfElse, Print,  # noqa: F401
                           StaticRNN, Switch, While, array_length,
                           array_read, array_write, create_array,
                           equal, greater_equal, greater_than,
                           is_empty, less_equal, less_than,
                           logical_and, logical_not, logical_or,
                           logical_xor, not_equal)
from . import detection  # noqa: F401
from .detection import *  # noqa: F401,F403
from .io import data  # noqa: F401
from .learning_rate_scheduler import (cosine_decay,  # noqa: F401
                                      exponential_decay,
                                      inverse_time_decay,
                                      linear_lr_warmup, natural_exp_decay,
                                      noam_decay, piecewise_decay,
                                      polynomial_decay)
from .metric_op import accuracy, auc, chunk_eval  # noqa: F401
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .rnn import (dynamic_gru, dynamic_lstm,  # noqa: F401
                  dynamic_lstmp, gru_unit, lstm, lstm_unit)
from .sequence import (beam_search, beam_search_decode,  # noqa: F401
                       sequence_conv, sequence_reshape,
                       sequence_scatter,
                       sequence_concat, sequence_enumerate,  # noqa: F401
                       sequence_expand, sequence_expand_as,
                       sequence_first_step, sequence_last_step,
                       sequence_pad, sequence_pool, sequence_reverse,
                       sequence_slice, sequence_softmax,
                       sequence_unpad)
from .tensor import (assign, cast, concat, create_global_var,  # noqa: F401
                     autoincreased_step_counter,
                     create_parameter, create_tensor, diag, eye,
                     fill_constant, fill_constant_batch_size_like,
                     linspace, ones, ones_like, pow, reverse, sum,
                     sums, tensor_array_to_tensor, zeros, zeros_like)
from .tensor import range as range_  # noqa: F401
from .tensor import range  # noqa: F401,A001  (reference export name)
