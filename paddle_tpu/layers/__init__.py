"""fluid.layers-equivalent namespace: every public layer in one place
(reference: python/paddle/fluid/layers/__init__.py)."""

from . import math_op_patch  # noqa: F401  (registers Variable operators)
from .io import data  # noqa: F401
from .metric_op import accuracy, auc  # noqa: F401
from .nn import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .tensor import (assign, cast, concat, create_global_var,  # noqa: F401
                     create_parameter, create_tensor, diag, eye,
                     fill_constant, fill_constant_batch_size_like,
                     linspace, ones, ones_like, sums, zeros, zeros_like)
from .tensor import range as range_  # noqa: F401
