"""Operator-overload support for Variable (reference:
python/paddle/fluid/layers/math_op_patch.py — monkey_patch_variable)."""

from __future__ import annotations

from .. import framework
from ..layer_helper import LayerHelper


def _scalar_to_var(block, value, dtype):
    helper = LayerHelper("scalar")
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    block.append_op(type="fill_constant", outputs={"Out": [out]},
                    attrs={"shape": (), "dtype": dtype,
                           "value": float(value)})
    return out


def binary(lhs, rhs, op_type, reverse=False):
    block = lhs.block
    if not isinstance(rhs, framework.Variable):
        rhs = _scalar_to_var(block, rhs, lhs.dtype)
    x, y = (rhs, lhs) if reverse else (lhs, rhs)
    helper = LayerHelper(op_type)
    cmp_ops = {"less_than", "less_equal", "greater_than", "greater_equal",
               "equal", "not_equal"}
    out_dtype = "bool" if op_type in cmp_ops else x.dtype
    out = helper.create_variable_for_type_inference(out_dtype)
    block.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                    outputs={"Out": [out]}, attrs={"axis": -1})
    return out
