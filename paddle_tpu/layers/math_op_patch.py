"""Operator-overload support for Variable (reference:
python/paddle/fluid/layers/math_op_patch.py — monkey_patch_variable)."""

from __future__ import annotations

from .. import framework
from ..layer_helper import LayerHelper


def _scalar_to_var(value, dtype):
    helper = LayerHelper("scalar")
    out = helper.create_variable_for_type_inference(dtype,
                                                    stop_gradient=True)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": (), "dtype": dtype,
                            "value": float(value)})
    return out


def binary(lhs, rhs, op_type, reverse=False):
    # Always append to the *current* block (which may be a control-flow
    # sub-block), not the block the lhs Variable was created in.
    if not isinstance(rhs, framework.Variable):
        rhs = _scalar_to_var(rhs, lhs.dtype)
    x, y = (rhs, lhs) if reverse else (lhs, rhs)
    helper = LayerHelper(op_type)
    cmp_ops = {"less_than", "less_equal", "greater_than", "greater_equal",
               "equal", "not_equal"}
    out_dtype = "bool" if op_type in cmp_ops else x.dtype
    out = helper.create_variable_for_type_inference(out_dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out
