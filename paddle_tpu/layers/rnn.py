"""Recurrent layers: dynamic_lstm / dynamic_gru / lstm_unit / gru_unit.

Reference: python/paddle/fluid/layers/nn.py (dynamic_lstm:443,
dynamic_gru, lstm_unit, gru_unit). Input layout is the padded+lengths
redesign — [batch, max_len, gates*hidden] pre-projected input plus an
optional per-row ``seq_len`` vector (see ops/rnn_ops.py for equations
and the lax.scan lowering)."""

from __future__ import annotations

from ..core.enforce import enforce
from ..layer_helper import LayerHelper

__all__ = ["dynamic_lstm", "dynamic_lstmp", "dynamic_gru",
           "lstm", "lstm_unit", "gru_unit"]


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 seq_len=None):
    """``input``: [B, T, 4*hidden] (apply fc(input, 4*hidden) first, as
    in the reference); ``size`` = 4*hidden. Returns (hidden, cell),
    each [B, T, hidden]."""
    return _dynamic_lstm_full(
        input, size, h_0=h_0, c_0=c_0, param_attr=param_attr,
        bias_attr=bias_attr, use_peepholes=use_peepholes,
        is_reverse=is_reverse, gate_activation=gate_activation,
        cell_activation=cell_activation,
        candidate_activation=candidate_activation, dtype=dtype,
        name=name, seq_len=seq_len)[:2]


def _dynamic_lstm_full(input, size, h_0=None, c_0=None,
                       param_attr=None, bias_attr=None,
                       use_peepholes=True, is_reverse=False,
                       gate_activation="sigmoid",
                       cell_activation="tanh",
                       candidate_activation="tanh", dtype="float32",
                       name=None, seq_len=None):
    """dynamic_lstm plus the op's last-step states ([B, hidden] each,
    seq_len-aware) — the lstm op computes them anyway; layers.lstm
    consumes them for the cudnn state contract."""
    enforce(size % 4 == 0, "dynamic_lstm size must be 4*hidden_size")
    helper = LayerHelper("lstm", name=name)
    hidden = size // 4
    weight = helper.create_parameter(attr=param_attr,
                                     shape=(hidden, 4 * hidden),
                                     dtype=dtype)
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(attr=bias_attr, shape=(1, bias_size),
                                   dtype=dtype, is_bias=True)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    out_h = helper.create_variable_for_type_inference(dtype)
    out_c = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lstm", inputs=inputs,
        outputs={"Hidden": [out_h], "Cell": [out_c],
                 "LastH": [last_h], "LastC": [last_c]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return out_h, out_c, last_h, last_c


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, name=None,
                dtype="float32", seq_len=None):
    """``input``: [B, T, 3*size] pre-projected; ``size`` = hidden.
    Returns hidden [B, T, size]."""
    helper = LayerHelper("gru", name=name)
    weight = helper.create_parameter(attr=param_attr,
                                     shape=(size, 3 * size), dtype=dtype)
    bias = helper.create_parameter(attr=bias_attr, shape=(1, 3 * size),
                                   dtype=dtype, is_bias=True)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    out_h = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gru", inputs=inputs,
        outputs={"Hidden": [out_h], "LastH": [last_h]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "candidate_activation": candidate_activation})
    return out_h


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference: nn.py lstm_unit): one fc over
    concat([x_t, h_prev]) — param_attr/bias_attr govern that single
    weight, exactly as in the reference — then the cell math.
    Returns (hidden, cell)."""
    from . import nn
    from .tensor import concat
    helper = LayerHelper("lstm_unit", name=name)
    hidden = hidden_t_prev.shape[-1]
    proj = nn.fc(concat([x_t, hidden_t_prev], axis=1),
                 size=4 * hidden, param_attr=param_attr,
                 bias_attr=bias_attr)
    out_h = helper.create_variable_for_type_inference(x_t.dtype)
    out_c = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [proj], "HPrev": [hidden_t_prev],
                "CPrev": [cell_t_prev]},
        outputs={"H": [out_h], "C": [out_c]},
        attrs={"forget_bias": float(forget_bias)})
    return out_h, out_c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """Single GRU step (reference: nn.py gru_unit). ``input``:
    [B, 3*size] pre-projected. Returns new hidden [B, size]."""
    helper = LayerHelper("gru_unit", name=name)
    weight = helper.create_parameter(attr=param_attr,
                                     shape=(size, 3 * size),
                                     dtype=input.dtype)
    bias = helper.create_parameter(attr=bias_attr, shape=(1, 3 * size),
                                   dtype=input.dtype, is_bias=True)
    out_h = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="gru_unit",
        inputs={"X": [input], "HPrev": [hidden], "Weight": [weight],
                "Bias": [bias]},
        outputs={"H": [out_h]},
        attrs={"gate_activation": gate_activation,
               "activation": activation})
    return out_h


def dynamic_lstmp(input, size, proj_size, param_attr=None,
                  bias_attr=None, use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None,
                  h_0=None, c_0=None, seq_len=None):
    """LSTM with a recurrent projection layer (reference: layers/nn.py
    dynamic_lstmp -> lstmp_op.cc). ``input``: [B, T, 4*hidden]
    pre-projected; returns (projection, cell)."""
    enforce(size % 4 == 0, "dynamic_lstmp size must be 4*hidden_size")
    helper = LayerHelper("lstmp", name=name)
    hidden = size // 4
    weight = helper.create_parameter(attr=param_attr,
                                     shape=(proj_size, 4 * hidden),
                                     dtype=dtype)
    proj_weight = helper.create_parameter(attr=param_attr,
                                          shape=(hidden, proj_size),
                                          dtype=dtype)
    bias_size = 7 * hidden if use_peepholes else 4 * hidden
    bias = helper.create_parameter(attr=bias_attr,
                                   shape=(1, bias_size), dtype=dtype,
                                   is_bias=True)
    inputs = {"Input": [input], "Weight": [weight],
              "ProjWeight": [proj_weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="lstmp", inputs=inputs,
        outputs={"Projection": [proj], "Cell": [cell],
                 "LastH": [last_h], "LastC": [last_c]},
        attrs={"use_peepholes": use_peepholes,
               "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return proj, cell


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1, seq_len=None):
    """Multi-layer LSTM (reference: layers/nn.py lstm — the cudnn LSTM
    wrapper; here each layer is the scan-lowered lstm op, stacked, and
    the input carries its own projection per layer as the cudnn weight
    blob did). ``input`` [B, T, D]; ``init_h``/``init_c``
    [num_layers, B, hidden] (or None for zeros). Returns (out,
    last_h, last_c) with ``out`` [B, T, hidden] the FINAL layer's
    sequence and last_h/last_c [num_layers, B, hidden] the last-step
    states — the cudnn contract. Dropout is applied between layers
    only, as cudnn does."""
    from . import nn as _nn
    enforce(not is_bidirec, "is_bidirec=True: use two stacks with "
            "is_reverse and concat (cudnn bidirectional blob layout "
            "has no TPU analog)")
    helper = LayerHelper("lstm_stack", name=name)

    def layer_state(state, layer):
        if state is None:
            return None
        return _nn.squeeze(_nn.slice(state, axes=[0], starts=[layer],
                                     ends=[layer + 1]), axes=[0])

    x = input
    last_hs, last_cs = [], []
    for layer in range(num_layers):
        if layer > 0 and dropout_prob and not is_test:
            # cudnn semantics: dropout between layers, never on the
            # final layer's output
            x = _nn.dropout(x, dropout_prob)
        proj = _nn.fc(x, 4 * hidden_size, num_flatten_dims=2,
                      bias_attr=False,
                      name=(name or "lstm") + "_in%d" % layer)
        h, _c, lh, lc = _dynamic_lstm_full(
            proj, 4 * hidden_size,
            h_0=layer_state(init_h, layer),
            c_0=layer_state(init_c, layer),
            use_peepholes=False,
            name=(name or "lstm") + "_l%d" % layer,
            seq_len=seq_len)
        x = h
        last_hs.append(lh)
        last_cs.append(lc)
    return (x, _nn.stack(last_hs, axis=0),
            _nn.stack(last_cs, axis=0))
