"""In-graph learning-rate schedules over a global step counter
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py —
noam_decay:36, exponential_decay:66, natural_exp_decay:102,
inverse_time_decay:133, polynomial_decay:165, piecewise_decay:214,
cosine_decay:254, linear_lr_warmup:282).

Each schedule appends ops computing the current LR from a persistable
step counter that increments once per executed step — so the schedule
compiles into the same XLA program as the train step (the reference runs
these as ops in the main program too)."""

from __future__ import annotations

import math

from .. import unique_name
from ..initializer import Constant
from ..layer_helper import LayerHelper
from . import nn, ops, tensor
from .control_flow import less_than

__all__ = ["noam_decay", "exponential_decay", "natural_exp_decay",
           "inverse_time_decay", "polynomial_decay", "piecewise_decay",
           "cosine_decay", "linear_lr_warmup"]


def _decay_step_counter(begin=0):
    """Auto-incrementing global step (reference
    layers/learning_rate_scheduler.py _decay_step_counter → autoincreased
    step counter var). Returns a float32 scalar holding the 0-based step
    index of the current run."""
    helper = LayerHelper("global_step_counter")
    counter = helper.main_program.global_block().create_var(
        name=unique_name.generate("@LR_DECAY_COUNTER@"),
        shape=(), dtype="int64", persistable=True, stop_gradient=True)
    sblock = helper.startup_program.global_block()
    sv = sblock.create_var(name=counter.name, shape=(), dtype="int64",
                           persistable=True, stop_gradient=True)
    Constant(float(begin))(sv, sblock)
    nn.increment(counter, value=1, in_place=True)
    # 0-based step index of *this* run = counter_after_increment - 1
    step = nn.cast(counter, "float32")
    return nn.scale(step, scale=1.0, bias=-1.0)


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference :36; the transformer schedule)."""
    step = _one_based_step()
    a = ops.rsqrt(step)
    b = nn.scale(step, scale=float(warmup_steps) ** -1.5)
    lr = nn.elementwise_min(a, b)
    return nn.scale(lr, scale=float(d_model) ** -0.5)


def _one_based_step():
    s = _decay_step_counter()
    return nn.scale(s, scale=1.0, bias=1.0)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate ^ (step / decay_steps) (reference :66)."""
    step = _decay_step_counter()
    div = nn.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        div = ops.floor(div)
    factor = nn.elementwise_pow(
        tensor.fill_constant((), "float32", float(decay_rate)), div)
    return nn.scale(factor, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * exp(-decay_rate * step / decay_steps) (reference :102)."""
    step = _decay_step_counter()
    div = nn.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        div = ops.floor(div)
    return nn.scale(ops.exp(nn.scale(div, scale=-float(decay_rate))),
                    scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * step / decay_steps) (reference :133)."""
    step = _decay_step_counter()
    div = nn.scale(step, scale=1.0 / float(decay_steps))
    if staircase:
        div = ops.floor(div)
    denom = nn.scale(div, scale=float(decay_rate), bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant((), "float32", float(learning_rate)), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    """Polynomial ramp from lr to end_lr over decay_steps (reference
    :165)."""
    step = _decay_step_counter()
    if cycle:
        div = ops.ceil(nn.scale(step, scale=1.0 / float(decay_steps)))
        # first step: ceil(0) == 0 -> force 1 so lr starts at base
        one = tensor.fill_constant((), "float32", 1.0)
        div = nn.elementwise_max(div, one)
        decay_steps_v = nn.scale(div, scale=float(decay_steps))
        frac = nn.elementwise_div(step, decay_steps_v)
    else:
        cap = tensor.fill_constant((), "float32", float(decay_steps))
        step = nn.elementwise_min(step, cap)
        frac = nn.scale(step, scale=1.0 / float(decay_steps))
    one_minus = nn.scale(frac, scale=-1.0, bias=1.0)
    poly = nn.elementwise_pow(
        one_minus, tensor.fill_constant((), "float32", float(power)))
    return nn.scale(poly,
                    scale=float(learning_rate) - float(end_learning_rate),
                    bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    """Step function: values[i] while step < boundaries[i] (reference
    :214). Computed branch-free as sum of interval indicators — XLA
    prefers the arithmetic form to a switch chain."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    step = _decay_step_counter()
    lr = tensor.fill_constant((), "float32", float(values[-1]))
    # lr = values[-1] + sum_i (values[i] - values[i+1]) * (step < b_i)
    for i, b in enumerate(boundaries):
        below = nn.cast(
            less_than(step,
                         tensor.fill_constant((), "float32", float(b))),
            "float32")
        delta = nn.scale(below,
                         scale=float(values[i]) - float(values[i + 1]))
        lr = nn.elementwise_add(lr, delta)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr * 0.5 * (cos(pi * epoch / epochs) + 1) (reference :254)."""
    step = _decay_step_counter()
    epoch = ops.floor(nn.scale(step, scale=1.0 / float(step_each_epoch)))
    inner = nn.scale(epoch, scale=math.pi / float(epochs))
    return nn.scale(ops.cos(inner), scale=0.5 * float(learning_rate),
                    bias=0.5 * float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp start_lr→end_lr for warmup_steps, then the wrapped
    schedule (reference :282). learning_rate may be a float or a
    schedule output Variable."""
    from ..framework import Variable
    step = _decay_step_counter()
    if not isinstance(learning_rate, Variable):
        learning_rate = tensor.fill_constant(
            (), "float32", float(learning_rate))
    frac = nn.scale(step, scale=1.0 / float(warmup_steps))
    warm = nn.scale(frac, scale=float(end_lr) - float(start_lr),
                    bias=float(start_lr))
    in_warmup = nn.cast(
        less_than(step, tensor.fill_constant(
            (), "float32", float(warmup_steps))), "float32")
    keep = nn.scale(in_warmup, scale=-1.0, bias=1.0)
    return nn.elementwise_add(nn.elementwise_mul(warm, in_warmup),
                              nn.elementwise_mul(learning_rate, keep))
