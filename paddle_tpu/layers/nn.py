"""User-facing layers API — parametric layers and NN ops.

Reference: python/paddle/fluid/layers/nn.py (12k LoC, 171 defs: fc:211,
embedding, conv2d, pool2d, batch_norm, layer_norm, dropout, ...). Same
names and signatures (modulo LoD-specific args); each call appends ops to
the default main program via LayerHelper.
"""

from __future__ import annotations

from .. import framework
from ..core.enforce import enforce
from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import Constant


def _simple(op_type, x, attrs=None, name=None, extra_inputs=None,
            out_dtype=None, stop_gradient=False):
    helper = LayerHelper(op_type, name=name)
    inputs = {"X": [x]}
    if extra_inputs:
        inputs.update(extra_inputs)
    out = helper.create_variable_for_type_inference(
        out_dtype or x.dtype, stop_gradient=stop_gradient)
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs or {})
    return out


# ---------------------------------------------------------------------------
# fc / embedding
# ---------------------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected layer (reference: layers/nn.py:211). Multiple
    inputs are each projected then summed, as in fluid."""
    helper = LayerHelper("fc", name=name, act=act)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        enforce(
            inp.shape is not None
            and len(inp.shape) > num_flatten_dims,
            "fc input %r needs a known rank > num_flatten_dims=%d to "
            "size its weight (got shape %r — if this is an op whose "
            "shape inference failed, set FLAGS_infer_shape_debug=1 to "
            "see why)" % (inp.name, num_flatten_dims, inp.shape))
        in_features = 1
        for d in inp.shape[num_flatten_dims:]:
            in_features *= d
        w = helper.create_parameter(attr=pattr,
                                    shape=(in_features, size),
                                    dtype=inp.dtype)
        out = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(type="mul", inputs={"X": [inp], "Y": [w]},
                         outputs={"Out": [out]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(
            inputs[0].dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=(size,),
                                    dtype=pre_bias.dtype, is_bias=True)
        pre_act = helper.append_bias_op(pre_bias, b,
                                        axis=num_flatten_dims)
    else:
        pre_act = pre_bias
    return helper.append_activation(pre_act)


def fused_linear_cross_entropy(input, label, size, epsilon=0.0,
                               param_attr=None, name=None,
                               return_logits=False):
    """Fused vocabulary projection + label-smoothed softmax
    cross-entropy over the last axis of ``input``:
    ``loss = softmax_xent(input @ W, smooth(onehot(label), epsilon))``.

    The TPU replacement for the ``fc + label_smooth +
    softmax_with_cross_entropy`` chain every NMT/LM model ends with
    (reference: operators/fused/ fusion pattern + math/cross_entropy.cu)
    — the [N, vocab] logits are the model's largest activation, and the
    fused op (pallas variant: ops/pallas/fused_xent.py) streams them
    through VMEM instead of materializing them in HBM.

    ``return_logits=True`` additionally emits the plain logits through
    a separate mul on the same weight — for inference graphs; when the
    logits go unfetched at train time XLA dead-code-eliminates the
    extra matmul, so emitting both costs nothing.

    Returns ``loss`` ([..., 1] float32), or ``(loss, logits)``.
    """
    helper = LayerHelper("fused_linear_xent", name=name)
    in_features = input.shape[-1]
    w = helper.create_parameter(attr=param_attr,
                                shape=(in_features, size),
                                dtype=input.dtype)
    loss = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="fused_linear_xent",
                     inputs={"X": [input], "W": [w], "Label": [label]},
                     outputs={"Loss": [loss]},
                     attrs={"epsilon": epsilon})
    if not return_logits:
        return loss
    logits = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="mul", inputs={"X": [input], "Y": [w]},
                     outputs={"Out": [logits]},
                     attrs={"x_num_col_dims": len(input.shape) - 1,
                            "y_num_col_dims": 1})
    return loss, logits


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32",
              name=None):
    """Reference: layers/nn.py embedding -> lookup_table_op.cc. On TPU
    the table is a dense HBM array; ``is_sparse`` is accepted for parity
    (XLA's gather/scatter-add covers the SelectedRows path).

    ``is_distributed=True`` requests a table too large for device HBM:
    no parameter is created — the rows live host-side across pserver
    processes (distributed.LargeScaleKV) and the lookup result enters
    the program as a feed-like data var. The runtime
    (distributed.SparseEmbeddingRuntime) prefetches the batch's rows
    before each step and pushes the sparse grads after — the analog of
    _replace_lookup_table_op_with_prefetch
    (distribute_transpiler.py:1372) + parameter_prefetch.cc."""
    helper = LayerHelper("embedding", name=name)
    if is_distributed:
        from .. import unique_name
        # a ParamAttr name pins the table id across processes (server
        # and trainer must agree on it — same contract as dense param
        # names under unique_name.guard); _to_attr so the plain-str
        # spelling every other layer accepts works here too
        from ..param_attr import ParamAttr
        attr = ParamAttr._to_attr(param_attr) \
            if param_attr is not None else None
        attr_name = attr.name if isinstance(attr, ParamAttr) else None
        table = attr_name or name or unique_name.generate("dist_table")
        out_shape = tuple(input.shape) + (size[1],)
        out = helper.main_program.global_block().create_var(
            name=unique_name.generate(table + "_prefetch"),
            shape=out_shape, dtype=dtype, is_data=True)
        meta = getattr(helper.main_program, "_distributed_lookups", None)
        if meta is None:
            meta = helper.main_program._distributed_lookups = []
        pad = None if padding_idx is None else \
            (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
        meta.append({"table": table, "ids": input.name,
                     "out": out.name, "rows": size[0],
                     "dim": size[1], "padding_idx": pad})
        return out
    w = helper.create_parameter(attr=param_attr, shape=tuple(size),
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    pad = -1 if padding_idx is None else \
        (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(type="lookup_table",
                     inputs={"W": [w], "Ids": [input]},
                     outputs={"Out": [out]},
                     attrs={"padding_idx": pad, "is_sparse": is_sparse,
                            "is_distributed": is_distributed})
    return out


# ---------------------------------------------------------------------------
# conv / pool / norm
# ---------------------------------------------------------------------------

def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    """Reference: layers/nn.py conv2d. use_cudnn accepted for parity and
    ignored — XLA owns algorithm choice on TPU."""
    helper = LayerHelper("conv2d", name=name, act=act)

    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    fsize = _pair(filter_size)
    channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    enforce(channels % groups == 0, "channels %% groups != 0")
    w_shape = (num_filters, channels // groups) + fsize
    from ..initializer import MSRAInitializer
    w = helper.create_parameter(
        attr=param_attr, shape=w_shape, dtype=input.dtype,
        default_initializer=MSRAInitializer(uniform=False))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="conv2d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": _pair(stride),
                            "paddings": _pair(padding),
                            "dilations": _pair(dilation),
                            "groups": groups,
                            "data_format": data_format})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=(num_filters,),
                                    dtype=input.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=1)
    return helper.append_activation(out)


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None,
                     output_size=None):
    helper = LayerHelper("conv2d_transpose", name=name, act=act)

    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    fsize = _pair(filter_size)
    channels = input.shape[1]
    w = helper.create_parameter(
        attr=param_attr, shape=(channels, num_filters // groups) + fsize,
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="conv2d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": _pair(stride),
                            "paddings": _pair(padding),
                            "dilations": _pair(dilation),
                            "groups": groups,
                            "output_size": output_size})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=(num_filters,),
                                    dtype=input.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=1)
    return helper.append_activation(out)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None):
    helper = LayerHelper("conv3d", name=name, act=act)

    def _trip(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)

    fsize = _trip(filter_size)
    channels = input.shape[1]
    w = helper.create_parameter(
        attr=param_attr, shape=(num_filters, channels // groups) + fsize,
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="conv3d",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": _trip(stride),
                            "paddings": _trip(padding),
                            "dilations": _trip(dilation),
                            "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=(num_filters,),
                                    dtype=input.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=1)
    return helper.append_activation(out)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None,
           data_format="NCHW"):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"ksize": pool_size,
                            "pooling_type": pool_type,
                            "strides": pool_stride,
                            "paddings": pool_padding,
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode,
                            "exclusive": exclusive,
                            "data_format": data_format})
    return out


def adaptive_pool2d(input, pool_size, pool_type="avg", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="adaptive_pool2d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pool_size": pool_size,
                            "pooling_type": pool_type})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, moving_mean_name=None,
               moving_variance_name=None, use_global_stats=False):
    """Reference: layers/nn.py batch_norm -> batch_norm_op.cc. Running
    mean/var are persistable vars updated in-graph each step (MeanOut
    aliases Mean), matching the reference's in-place update."""
    helper = LayerHelper("batch_norm", name=name, act=act)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" and len(input.shape) == 4 \
        else input.shape[-1]
    if len(input.shape) == 2:
        c = input.shape[1]
    scale = helper.create_parameter(attr=param_attr, shape=(c,),
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(attr=bias_attr, shape=(c,),
                                   dtype=dtype, is_bias=True)
    mean = _bn_stat(helper, moving_mean_name, c, dtype, 0.0)
    var = _bn_stat(helper, moving_variance_name, c, dtype, 1.0)
    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [var]},
        outputs={"Y": [y], "MeanOut": [mean], "VarianceOut": [var],
                 "SavedMean": [saved_mean],
                 "SavedVariance": [saved_var]},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "is_test": is_test, "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(y)


def _bn_stat(helper, name, c, dtype, init_val):
    """Create a moving-stat persistable var + startup init."""
    from .. import unique_name
    vname = name or unique_name.generate(helper.name + ".moving")
    v = helper.main_program.global_block().create_var(
        name=vname, shape=(c,), dtype=dtype, persistable=True,
        stop_gradient=True)
    sblock = helper.startup_program.global_block()
    sv = sblock.create_var(name=vname, shape=(c,), dtype=dtype,
                           persistable=True, stop_gradient=True)
    Constant(init_val)(sv, sblock)
    return v


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """Reference: layers/nn.py layer_norm -> layer_norm_op.cc (pallas
    fused variant available, ops/pallas/layer_norm.py)."""
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = input.dtype
    nshape = 1
    for d in input.shape[begin_norm_axis:]:
        nshape *= d
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(attr=param_attr, shape=(nshape,),
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(attr=bias_attr, shape=(nshape,),
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(dtype)
    # the op emits statistics in f32 regardless of input dtype
    mean = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    var = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [mean],
                              "Variance": [var]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(y)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, name=None):
    helper = LayerHelper("group_norm", name=name, act=act)
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(attr=param_attr, shape=(c,),
                                    dtype=input.dtype,
                                    default_initializer=Constant(1.0))
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=(c,),
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference(input.dtype,
                                                     stop_gradient=True)
    var = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    helper.append_op(type="group_norm", inputs=inputs,
                     outputs={"Y": [y], "Mean": [mean],
                              "Variance": [var]},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(y)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype,
                                                     stop_gradient=True)
    helper.append_op(type="dropout", inputs={"X": [x]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"dropout_prob": dropout_prob,
                            "is_test": is_test, "seed": seed or 0,
                            "dropout_implementation":
                                dropout_implementation})
    return out


# ---------------------------------------------------------------------------
# losses / softmax
# ---------------------------------------------------------------------------

def softmax(input, axis=-1, use_cudnn=False, name=None):
    return _simple("softmax", input, {"axis": axis}, name)


def log_softmax(input, axis=-1, name=None):
    return _simple("log_softmax", input, {"axis": axis}, name)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    helper = LayerHelper("softmax_with_cross_entropy")
    sm = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [sm], "Loss": [loss]},
                     attrs={"soft_label": soft_label,
                            "ignore_index": ignore_index, "axis": axis})
    if return_softmax:
        return loss, sm
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="square_error_cost",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]})
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"ignore_index": ignore_index,
                            "normalize": normalize})
    return out


def smooth_l1(x, y, sigma=1.0):
    helper = LayerHelper("smooth_l1_loss")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="smooth_l1_loss",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"sigma": sigma})
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="huber_loss",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out]}, attrs={"delta": delta})
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="kldiv_loss",
                     inputs={"X": [x], "Target": [target]},
                     outputs={"Loss": [out]},
                     attrs={"reduction": reduction})
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="log_loss",
                     inputs={"Predicted": [input], "Labels": [label]},
                     outputs={"Loss": [out]}, attrs={"epsilon": epsilon})
    return out


# ---------------------------------------------------------------------------
# reductions / simple math
# ---------------------------------------------------------------------------

def mean(x, name=None):
    return _simple("mean", x, name=name)


def _reduce(op_type, input, dim, keep_dim, name):
    return _simple(op_type, input,
                   {"dim": dim, "keep_dim": keep_dim,
                    "reduce_all": dim is None}, name)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _simple("reduce_all", input,
                   {"dim": dim, "keep_dim": keep_dim,
                    "reduce_all": dim is None}, name, out_dtype="bool",
                   stop_gradient=True)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _simple("reduce_any", input,
                   {"dim": dim, "keep_dim": keep_dim,
                    "reduce_all": dim is None}, name, out_dtype="bool",
                   stop_gradient=True)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"scale": scale, "bias": bias,
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def _elementwise(op_type, x, y, axis, act, name):
    helper = LayerHelper(op_type, name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_x": transpose_x,
                            "transpose_y": transpose_y, "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def clip(x, min, max, name=None):
    return _simple("clip", x, {"min": min, "max": max}, name)


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", x, {"max_norm": max_norm}, name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _simple("norm", x, {"axis": axis, "epsilon": epsilon}, name)


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

def reshape(x, shape, actual_shape=None, act=None, inplace=False,
            name=None):
    helper = LayerHelper("reshape2", name=name, act=act)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="reshape2", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": tuple(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    return _simple("transpose2", x, {"axis": tuple(perm)}, name)


def squeeze(input, axes, name=None):
    return _simple("squeeze2", input, {"axes": tuple(axes)}, name)


def unsqueeze(input, axes, name=None):
    return _simple("unsqueeze2", input, {"axes": tuple(axes)}, name)


def flatten(x, axis=1, name=None):
    return _simple("flatten2", x, {"axis": axis}, name)


def expand(x, expand_times, name=None):
    return _simple("expand", x, {"expand_times": tuple(expand_times)},
                   name)


def slice(input, axes, starts, ends):
    return _simple("slice", input,
                   {"axes": tuple(axes), "starts": tuple(starts),
                    "ends": tuple(ends)})


def shape(input):
    return _simple("shape", input, out_dtype="int32", stop_gradient=True)


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype)
    helper.append_op(type="stack", inputs={"X": xs},
                     outputs={"Y": [out]}, attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    n = num if num is not None else x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype)
            for _ in range(n)]
    helper.append_op(type="unstack", inputs={"X": [x]},
                     outputs={"Y": outs},
                     attrs={"axis": axis, "num": n})
    return outs


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(type="concat", inputs={"X": input},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def split(input, num_or_sections, dim=0, name=None):
    helper = LayerHelper("split", name=name)
    n = num_or_sections if isinstance(num_or_sections, int) \
        else len(num_or_sections)
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(n)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"num_or_sections": num_or_sections,
                            "axis": dim})
    return outs


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gather",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]}, attrs={"axis": 0})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gather_nd",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]},
                     attrs={"overwrite": overwrite})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", x, {"paddings": tuple(paddings),
                              "pad_value": pad_value}, name)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return _simple("pad2d", input,
                   {"paddings": tuple(paddings), "mode": mode,
                    "pad_value": pad_value, "data_format": data_format},
                   name)


def one_hot(input, depth, allow_out_of_range=False):
    return _simple("one_hot", input, {"depth": depth},
                   out_dtype="float32", stop_gradient=True)


def cast(x, dtype):
    from ..framework import convert_dtype
    return _simple("cast", x, {"dtype": convert_dtype(dtype)},
                   out_dtype=convert_dtype(dtype))


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    vals = helper.create_variable_for_type_inference(input.dtype,
                                                     stop_gradient=True)
    idx = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [vals], "Indices": [idx]},
                     attrs={"k": k})
    return vals, idx


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    vals = helper.create_variable_for_type_inference(input.dtype,
                                                     stop_gradient=True)
    idx = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    helper.append_op(type="argsort", inputs={"X": [input]},
                     outputs={"Out": [vals], "Indices": [idx]},
                     attrs={"axis": axis, "descending": descending})
    return vals, idx


def argmax(x, axis=0):
    return _simple("arg_max", x, {"axis": axis}, out_dtype="int64",
                   stop_gradient=True)


def argmin(x, axis=0):
    return _simple("arg_min", x, {"axis": axis}, out_dtype="int64",
                   stop_gradient=True)


def where(condition, x, y):
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="where",
                     inputs={"Condition": [condition], "X": [x],
                             "Y": [y]},
                     outputs={"Out": [out]})
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    return _simple("cumsum", x, {"axis": axis, "exclusive": exclusive,
                                 "reverse": reverse})


def sequence_mask(x, maxlen, dtype="float32", name=None):
    return _simple("sequence_mask", x, {"maxlen": maxlen, "dtype": dtype},
                   name, out_dtype=dtype, stop_gradient=True)


def resize_bilinear(input, out_shape, name=None, align_corners=True):
    return _simple("interpolate", input,
                   {"out_shape": tuple(out_shape), "method": "bilinear",
                    "align_corners": align_corners}, name)


def resize_nearest(input, out_shape, name=None, align_corners=True):
    return _simple("interpolate", input,
                   {"out_shape": tuple(out_shape), "method": "nearest",
                    "align_corners": align_corners}, name)


def pixel_shuffle(x, upscale_factor):
    return _simple("pixel_shuffle", x,
                   {"upscale_factor": upscale_factor})


def grid_sampler(x, grid, name=None):
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="grid_sampler",
                     inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def maxout(x, groups, name=None, axis=1):
    return _simple("maxout", x, {"groups": groups, "axis": axis}, name)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": value})
    return out


def fsp_matrix(x, y):
    """Flow-of-solution-procedure matrix between two feature maps of
    the same spatial size (reference: layers/nn.py fsp_matrix ->
    operators/fsp_op.cc); used by the FSP distiller."""
    helper = LayerHelper("fsp_matrix")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="fsp_matrix", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    """Reference: layers/nn.py label_smooth -> label_smooth_op.cc."""
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"epsilon": float(epsilon)})
    return out


def scaled_dot_product_attention(q, k, v, bias=None, scale=1.0,
                                 dropout_rate=0.0, causal=False,
                                 is_test=False, name=None):
    """Fused attention core: softmax(q @ k^T * scale + bias) @ v over
    [batch, heads, seq, head_dim] inputs, with optional in-kernel
    attention dropout and causal masking. Lowers to one fused op (pallas
    flash kernel — blocked online softmax, recompute backward — when
    FLAGS_op_library=pallas; XLA-fused composite otherwise). ``bias`` is
    an additive attention *mask* (non-differentiable); add a trainable
    bias with elementwise_add instead. See ops/pallas/attention.py."""
    helper = LayerHelper("sdpa", name=name)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(type="scaled_dot_product_attention",
                     inputs=inputs, outputs={"Out": [out]},
                     attrs={"scale": float(scale),
                            "dropout_rate": float(dropout_rate),
                            "causal": bool(causal),
                            "is_test": bool(is_test)})
    return out


def moe_ffn(x, num_experts, d_ffn, capacity_factor=1.25, top_k=1,
            param_attr=None, name=None):
    """Mixture-of-experts FFN layer over ``[tokens, d_model]`` input:
    Switch (top_k=1) / GShard top-2 routing into ``num_experts``
    relu-FFN experts of width ``d_ffn`` (parallel/moe.py). Returns
    ``(out [tokens, d_model], aux_loss scalar)`` — add the aux loss
    (scaled) into the training objective to regularize routing.

    Under a CompiledProgram mesh with an ``ep`` axis the op runs
    expert-parallel: expert weights shard over ``ep`` on their leading
    E dim, tokens data-shard over the same axis, and one capacity-
    bucketed ``all_to_all`` each way moves only the dispatched tokens
    across ICI. Without an ep axis it is the exact single-device
    reference — the same program serves both, like the attention ops."""
    helper = LayerHelper("moe_ffn", name=name)
    enforce(x.shape is not None and len(x.shape) == 2,
            "moe_ffn wants [tokens, d_model] input (flatten sequence "
            "dims first), got shape %r" % (x.shape,))
    d_model = int(x.shape[1])
    E, F = int(num_experts), int(d_ffn)
    gate_w = helper.create_parameter(attr=param_attr,
                                     shape=(d_model, E), dtype=x.dtype)
    w1 = helper.create_parameter(attr=param_attr, shape=(E, d_model, F),
                                 dtype=x.dtype)
    b1 = helper.create_parameter(attr=param_attr, shape=(E, F),
                                 dtype=x.dtype, is_bias=True)
    w2 = helper.create_parameter(attr=param_attr, shape=(E, F, d_model),
                                 dtype=x.dtype)
    b2 = helper.create_parameter(attr=param_attr, shape=(E, d_model),
                                 dtype=x.dtype, is_bias=True)
    # expert weights shard over ep on the leading E axis; the mesh-less
    # case ignores the annotation (PartitionSpec axes not in the mesh
    # never bind)
    from ..parallel.api import shard as _shard
    _shard(w1, "ep", None, None)
    _shard(b1, "ep", None)
    _shard(w2, "ep", None, None)
    _shard(b2, "ep", None)
    out = helper.create_variable_for_type_inference(x.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="moe_ffn",
                     inputs={"X": [x], "GateW": [gate_w], "W1": [w1],
                             "B1": [b1], "W2": [w2], "B2": [b2]},
                     outputs={"Out": [out], "AuxLoss": [aux]},
                     attrs={"capacity_factor": float(capacity_factor),
                            "top_k": int(top_k)})
    return out, aux


# ---------------------------------------------------------------------------
# sequence-labeling / sampled losses (reference: layers/nn.py warpctc,
# edit_distance, linear_chain_crf, crf_decoding, nce, hsigmoid,
# sampled_softmax_with_cross_entropy, rank_loss, bpr_loss, cos_sim)
# ---------------------------------------------------------------------------

def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (reference: layers/nn.py warpctc -> warpctc_op.cc).
    Padded redesign: input [B, T, C] with input_length, label [B, L]
    with label_length (the LoD form has no padded equivalent)."""
    enforce(input_length is not None and label_length is not None,
            "padded CTC needs input_length and label_length")
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input], "Label": [label],
                "LogitsLength": [input_length],
                "LabelLength": [label_length]},
        outputs={"Loss": [loss]},
        attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank, input_length=None):
    """Greedy CTC decode: argmax per frame, collapse repeats, strip
    blanks (reference: layers/nn.py ctc_greedy_decoder)."""
    helper = LayerHelper("ctc_align")
    ids = argmax(input, axis=-1)
    out = helper.create_variable_for_type_inference("int32")
    out_len = helper.create_variable_for_type_inference("int32")
    if input_length is None:
        from . import tensor as _t
        input_length = _t.fill_constant_batch_size_like(
            input, shape=[-1, 1], dtype="int64",
            value=input.shape[1] if len(input.shape) > 1 else 1)
    helper.append_op(
        type="ctc_align",
        inputs={"Input": [ids], "InputLength": [input_length]},
        outputs={"Output": [out], "OutputLength": [out_len]},
        attrs={"blank": blank, "merge_repeated": True})
    return out, out_len


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """Levenshtein distance (reference: layers/nn.py edit_distance)."""
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance",
        inputs={"Hyps": [input], "Refs": [label],
                "HypsLength": [input_length],
                "RefsLength": [label_length]},
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized})
    return out, seq_num


def linear_chain_crf(input, label, param_attr=None, length=None):
    """CRF log-likelihood; creates the [D+2, D] transition parameter
    (rows: start, stop, transitions — reference layout,
    linear_chain_crf_op.h)."""
    helper = LayerHelper("linear_chain_crf")
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=param_attr, shape=(size + 2, size), dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition],
                "Label": [label], "Length": [length]},
        outputs={"LogLikelihood": [ll]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode using a trained transition param (reference:
    layers/nn.py crf_decoding). ``param_attr`` may be the transition
    Variable itself or its ParamAttr/name.

    Reference semantics for ``label``: when given, the output is a 0/1
    CORRECTNESS mask (1 where the decoded tag differs from the label —
    crf_decoding_op.h sets output to the mismatch indicator) rather
    than the path itself."""
    helper = LayerHelper("crf_decoding")
    from ..framework import Variable as _Var
    if isinstance(param_attr, _Var):
        transition = param_attr
    else:
        name = getattr(param_attr, "name", param_attr)
        transition = helper.main_program.global_block().var(name)
    path = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="crf_decoding",
        inputs={"Emission": [input], "Transition": [transition],
                "Length": [length]},
        outputs={"ViterbiPath": [path]})
    if label is not None:
        from .control_flow import not_equal
        from .tensor import cast
        return cast(not_equal(path, label), "int64")
    return path


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None,
        name=None, sampler="uniform", custom_dist=None, seed=0,
        is_sparse=False):
    """Noise-contrastive estimation loss; creates the class weight and
    bias (reference: layers/nn.py nce -> nce_op.cc)."""
    if sample_weight is not None:
        from ..core.enforce import UnimplementedError
        raise UnimplementedError(
            "NCE sample_weight is not supported (the nce op weights "
            "every example equally); weight the returned per-example "
            "cost instead")
    helper = LayerHelper("nce", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=param_attr,
                                shape=(num_total_classes, dim),
                                dtype=input.dtype)
    b = None
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=(num_total_classes,),
                                    dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="nce",
        inputs={"Input": [input], "Weight": [w],
                "Bias": [b] if b is not None else [],
                "Label": [label]},
        outputs={"Cost": [cost]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples or 10,
               "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None,
             bias_attr=None, name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: layers/nn.py hsigmoid)."""
    helper = LayerHelper("hierarchical_sigmoid", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(attr=param_attr,
                                shape=(num_classes - 1, dim),
                                dtype=input.dtype)
    b = None
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=(num_classes - 1,),
                                    dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid",
        inputs={"X": [input], "W": [w],
                "Bias": [b] if b is not None else [],
                "Label": [label]},
        outputs={"Out": [out], "PreOut": [pre_out]},
        attrs={"num_classes": num_classes})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, seed=0):
    """Sampled softmax (reference: layers/nn.py
    sampled_softmax_with_cross_entropy -> sample_logits_op.cc +
    softmax_with_cross_entropy)."""
    helper = LayerHelper("sample_logits")
    sampled = helper.create_variable_for_type_inference(logits.dtype)
    new_label = helper.create_variable_for_type_inference("int64")
    samples = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="sample_logits",
        inputs={"Logits": [logits], "Labels": [label]},
        outputs={"SampledLogits": [sampled],
                 "SampledLabels": [new_label], "Samples": [samples]},
        attrs={"num_samples": num_samples, "seed": seed})
    loss = helper.create_variable_for_type_inference(logits.dtype)
    softmax = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [sampled], "Label": [new_label]},
        outputs={"Loss": [loss], "Softmax": [softmax]},
        attrs={"soft_label": False})
    return loss


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={"Out": [out]})
    return out


def bpr_loss(input, label, name=None):
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="bpr_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Out": [out]})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xn],
                              "YNorm": [yn]})
    return out


# ---------------------------------------------------------------------------
# vision layers (reference: layers/nn.py lrn, affine_channel, pool3d,
# spectral_norm, row_conv, bilinear_tensor_product, temporal_shift,
# shuffle_channel, space_to_depth, crop, pad_constant_like, multiplex,
# image resize aliases)
# ---------------------------------------------------------------------------

def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha,
                            "beta": beta})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None):
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="affine_channel",
                     inputs={"X": [x], "Scale": [scale],
                             "Bias": [bias]},
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout})
    return out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           name=None, exclusive=True):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)

    def _3(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v,) * 3

    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"ksize": _3(pool_size),
                            "pooling_type": pool_type,
                            "strides": _3(pool_stride),
                            "paddings": _3(pool_padding),
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode,
                            "exclusive": exclusive})
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Creates the persistable u/v power-iteration vectors (reference:
    layers/nn.py spectral_norm)."""
    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w_rest = 1
    for i, d in enumerate(weight.shape):
        if i != dim:
            w_rest *= d
    from ..initializer import Normal
    u = helper.create_parameter(attr=None, shape=(h,),
                                dtype=weight.dtype,
                                default_initializer=Normal(0, 1))
    v = helper.create_parameter(attr=None, shape=(w_rest,),
                                dtype=weight.dtype,
                                default_initializer=Normal(0, 1))
    u.stop_gradient = True
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(weight.dtype)
    helper.append_op(type="spectral_norm",
                     inputs={"Weight": [weight], "U": [u], "V": [v]},
                     outputs={"Out": [out]},
                     attrs={"dim": dim, "power_iters": power_iters,
                            "eps": eps})
    return out


def row_conv(input, future_context_size, param_attr=None,
             act=None):
    helper = LayerHelper("row_conv", act=act)
    filt = helper.create_parameter(
        attr=param_attr, shape=(future_context_size + 1,
                                input.shape[-1]),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [filt]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", name=name, act=act)
    w = helper.create_parameter(
        attr=param_attr, shape=(size, x.shape[-1], y.shape[-1]),
        dtype=x.dtype)
    b = None
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=(1, size),
                                    dtype=x.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="bilinear_tensor_product",
                     inputs={"X": [x], "Y": [y], "Weight": [w],
                             "Bias": [b] if b is not None else []},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    helper = LayerHelper("temporal_shift", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="temporal_shift", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"seg_num": seg_num,
                            "shift_ratio": shift_ratio})
    return out


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shuffle_channel", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"group": group})
    return out


def space_to_depth(x, blocksize, name=None):
    helper = LayerHelper("space_to_depth", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="space_to_depth", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"blocksize": blocksize})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="crop", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": tuple(shape),
                            "offsets_attr": tuple(offsets or
                                                  [0] * len(shape))})
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(type="pad_constant_like",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"pad_value": pad_value})
    return out


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"Ids": [index], "X": list(inputs)},
                     outputs={"Out": [out]})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(type="mean_iou",
                     inputs={"Predictions": [input],
                             "Labels": [label]},
                     outputs={"OutMeanIou": [miou],
                              "OutWrong": [wrong],
                              "OutCorrect": [correct]},
                     attrs={"num_classes": num_classes})
    return miou, wrong, correct


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """Image patches -> sequence (reference: layers/nn.py im2sequence
    -> im2sequence_op.cc)."""
    helper = LayerHelper("im2sequence", name=name)

    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    pad = padding if isinstance(padding, (list, tuple)) and \
        len(padding) == 4 else _pair(padding) * 2
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": _pair(filter_size),
                            "strides": _pair(stride),
                            "paddings": tuple(pad)})
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Run a user Python callable as an op (reference: layers/nn.py
    py_func -> py_func_op.cc). ``out`` vars must be pre-created with
    shapes/dtypes (create_variable); ``backward_func(*inputs,
    *outputs, *output_grads)`` returns input grads (None entries for
    non-differentiable inputs). Under jit the call lowers to a host
    callback (jax.pure_callback)."""
    if skip_vars_in_backward_input is not None:
        from ..core.enforce import UnimplementedError
        raise UnimplementedError(
            "py_func skip_vars_in_backward_input is not supported: "
            "backward_func always receives (*inputs, *outputs, "
            "*output_grads) positionally — drop unused parameters in "
            "the callable instead")
    from ..ops.py_func_op import register_py_func
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    fid = register_py_func(func, backward_func)
    helper.append_op(
        type="py_func", inputs={"X": list(xs)},
        outputs={"Out": list(outs)},
        attrs={"func_id": fid,
               "out_shapes": tuple(tuple(int(d) for d in o.shape)
                                   for o in outs),
               "out_dtypes": tuple(o.dtype for o in outs)})
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None,
              bias_attr=None, name=None):
    """Tree-based convolution (reference: layers/nn.py tree_conv ->
    tree_conv_op.cc). nodes_vector [B, N, F], edge_set [B, E, 2]."""
    helper = LayerHelper("tree_conv", name=name)
    F = nodes_vector.shape[-1]
    w = helper.create_parameter(
        attr=param_attr, shape=(F, 3, output_size, num_filters),
        dtype=nodes_vector.dtype)
    out = helper.create_variable_for_type_inference(
        nodes_vector.dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]}, attrs={"max_depth": max_depth})
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=bias_attr, shape=(1, 1, output_size, num_filters),
            dtype=nodes_vector.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=-1)
    if act:
        out = _simple(act, out)
    return out


# -- reference API-parity batch (round 3) -----------------------------------

def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple("brelu", x, {"t_min": t_min, "t_max": t_max},
                   name=name)


def soft_relu(x, threshold=40.0, name=None):
    return _simple("soft_relu", x, {"threshold": threshold}, name=name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple("stanh", x, {"scale_a": scale_a,
                                "scale_b": scale_b}, name=name)


def selu(x, scale=None, alpha=None, name=None):
    attrs = {}
    if scale is not None:
        attrs["scale"] = scale
    if alpha is not None:
        attrs["alpha"] = alpha
    return _simple("selu", x, attrs, name=name)


def adaptive_pool3d(input, pool_size, pool_type="avg", name=None):
    return _simple("adaptive_pool3d", input,
                   {"pool_size": pool_size, "pooling_type": pool_type},
                   name=name)


def conv3d_transpose(input, num_filters, filter_size, padding=0,
                     stride=1, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    """Reference: layers/nn.py conv3d_transpose ->
    conv_transpose_op.cc (3-D)."""
    helper = LayerHelper("conv3d_transpose", name=name, act=act)
    c_in = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size,) * 3
    w = helper.create_parameter(
        attr=param_attr, shape=(c_in, num_filters // groups) + tuple(fs),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="conv3d_transpose",
                     inputs={"Input": [input], "Filter": [w]},
                     outputs={"Output": [out]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=(num_filters,),
                                    dtype=input.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=1)
    return helper.append_activation(out)


def dice_loss(input, label, epsilon=1e-5):
    return _simple("dice_loss", input, {"epsilon": epsilon},
                   extra_inputs={"Label": [label]})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    helper = LayerHelper("npair_loss")
    out = helper.create_variable_for_type_inference(anchor.dtype)
    helper.append_op(type="npair_loss",
                     inputs={"Anchor": [anchor],
                             "Positive": [positive],
                             "Labels": [labels]},
                     outputs={"Out": [out]},
                     attrs={"l2_reg": l2_reg})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(type="margin_rank_loss",
                     inputs={"X1": [left], "X2": [right],
                             "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"margin": margin})
    return out


def teacher_student_sigmoid_loss(input, label,
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="teacher_student_sigmoid_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]},
                     attrs={"soft_max_up_bound": soft_max_up_bound,
                            "soft_max_lower_bound":
                                soft_max_lower_bound})
    return out


def similarity_focus(input, axis, indexes, name=None):
    return _simple("similarity_focus", input,
                   {"axis": axis, "indexes": tuple(indexes)},
                   name=name, stop_gradient=True)


def continuous_value_model(input, cvm, use_cvm=True):
    """Reference: layers/nn.py continuous_value_model -> cvm op."""
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="cvm",
                     inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]},
                     attrs={"use_cvm": use_cvm})
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(type="sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"min": min, "max": max, "seed": seed})
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """Reference: layers/nn.py data_norm -> data_norm_op.cc (CTR
    normalization with learned batch statistics)."""
    helper = LayerHelper("data_norm", name=name, act=act)
    c = input.shape[-1]
    size = helper.create_parameter(
        attr=param_attr, shape=(c,), dtype=input.dtype,
        default_initializer=Constant(1.0))
    sum_ = helper.create_parameter(
        attr=param_attr, shape=(c,), dtype=input.dtype,
        default_initializer=Constant(0.0))
    sqsum = helper.create_parameter(
        attr=param_attr, shape=(c,), dtype=input.dtype,
        default_initializer=Constant(1e-4))
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype)
    scales = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [size],
                "BatchSum": [sum_], "BatchSquareSum": [sqsum]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]},
        attrs={"epsilon": epsilon})
    return helper.append_activation(out)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    """Reference: layers/nn.py image_resize -> interpolate ops."""
    enforce(resample in ("BILINEAR", "NEAREST"),
            "resample must be BILINEAR or NEAREST")
    if out_shape is None:
        enforce(scale is not None, "need out_shape or scale")
        h, w = input.shape[2], input.shape[3]
        out_shape = (int(h * scale), int(w * scale))
    op = "bilinear_interp" if resample == "BILINEAR" \
        else "nearest_interp"
    return _simple(op, input,
                   {"out_h": int(out_shape[0]),
                    "out_w": int(out_shape[1]),
                    "align_corners": align_corners,
                    "align_mode": align_mode}, name=name)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = input.shape[2], input.shape[3]
    if h < w:
        oh, ow = out_short_len, int(w * out_short_len / h)
    else:
        oh, ow = int(h * out_short_len / w), out_short_len
    return image_resize(input, out_shape=(oh, ow), resample=resample)


def random_crop(x, shape, seed=None):
    from . import tensor as _t
    helper = LayerHelper("random_crop")
    if seed is None or isinstance(seed, int):
        seed_var = _t.fill_constant((1,), "int64", seed or 0)
    else:
        seed_var = seed
    out = helper.create_variable_for_type_inference(x.dtype)
    seed_out = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    helper.append_op(type="random_crop",
                     inputs={"X": [x], "Seed": [seed_var]},
                     outputs={"Out": [out], "SeedOut": [seed_out]},
                     attrs={"shape": tuple(shape)})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": tuple(shape), "mean": mean,
                            "std": std, "dtype": dtype})
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0,
                                    std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(type="gaussian_random_batch_size_like",
                     inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"shape": tuple(shape), "mean": mean,
                            "std": std, "dtype": dtype,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(type="uniform_random_batch_size_like",
                     inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"shape": tuple(shape), "min": min,
                            "max": max, "dtype": dtype,
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _simple("add_position_encoding", input,
                   {"alpha": alpha, "beta": beta}, name=name)


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    attrs = {}
    if isinstance(out_shape, (list, tuple)):
        attrs["output_shape_attr"] = tuple(out_shape)
        inputs = {"Theta": [theta]}
    else:
        inputs = {"Theta": [theta], "OutputShape": [out_shape]}
    helper.append_op(type="affine_grid", inputs=inputs,
                     outputs={"Output": [out]}, attrs=attrs)
    return out


def has_inf(x):
    return _simple("has_inf", x, out_dtype="bool", stop_gradient=True)


def has_nan(x):
    return _simple("has_nan", x, out_dtype="bool", stop_gradient=True)


def isfinite(x):
    return _simple("isfinite", x, out_dtype="bool",
                   stop_gradient=True)


def hash(input, hash_size, num_hash=1, name=None):
    return _simple("hash", input,
                   {"num_hash": num_hash, "mod_by": hash_size},
                   out_dtype="int64", stop_gradient=True, name=name)


def rank(input):
    """Rank (ndim) of a variable as a constant tensor (reference:
    layers/nn.py rank — build-time constant here, shapes are static)."""
    from . import tensor as _t
    import numpy as _np
    return _t.assign(_np.array([len(input.shape)], _np.int32))


def merge_selected_rows(x, name=None):
    return _simple("merge_selected_rows", x, name=name)


def get_tensor_from_selected_rows(x, name=None):
    return _simple("get_tensor_from_selected_rows", x, name=name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    from . import math_op_patch as mop
    return mop.binary(x, y, "elementwise_mod")


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    from . import math_op_patch as mop
    return mop.binary(x, y, "elementwise_floordiv")
