"""Auto-generated-style thin wrappers for unary/simple ops.

Reference: python/paddle/fluid/layers/ops.py (generated from OpProtos by
layer_function_generator.py). Here the registry IS the proto source: we
generate a wrapper per registered unary op.
"""

from __future__ import annotations

import sys

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "relu", "sigmoid", "tanh", "softplus", "softsign", "relu6",
    "logsigmoid", "exp", "log", "log1p", "sqrt", "rsqrt", "abs", "ceil",
    "floor", "round", "square", "reciprocal", "sign", "sin", "cos",
    "tan", "asin", "acos", "atan", "sinh", "cosh", "erf",
]


def _make_unary(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    layer.__doc__ = "Elementwise %s (see ops registry)." % op_type
    return layer


_mod = sys.modules[__name__]
for _op in _UNARY_OPS:
    setattr(_mod, _op, _make_unary(_op))


def _make_unary_bool(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference("bool")
        out.stop_gradient = True
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    layer.__doc__ = "Elementwise %s predicate." % op_type
    return layer


for _op in ("isnan", "isinf", "isfinite"):
    setattr(_mod, _op, _make_unary_bool(_op))


def gelu(x, approximate=True, name=None):
    helper = LayerHelper("gelu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="gelu", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"approximate": approximate})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="leaky_relu", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"alpha": alpha})
    return out


def elu(x, alpha=1.0, name=None):
    helper = LayerHelper("elu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="elu", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"alpha": alpha})
    return out


def swish(x, beta=1.0, name=None):
    helper = LayerHelper("swish", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="swish", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"beta": beta})
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    helper = LayerHelper("hard_sigmoid", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="hard_sigmoid", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"slope": slope, "offset": offset})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    from ..initializer import Constant
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = (1,)
    elif mode == "channel":
        alpha_shape = (x.shape[1],)
    else:
        alpha_shape = tuple(x.shape[1:])
    alpha = helper.create_parameter(attr=param_attr, shape=alpha_shape,
                                    dtype=x.dtype,
                                    default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="prelu",
                     inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out
