"""Detection layers — the fluid.layers.detection API surface.

Reference: python/paddle/fluid/layers/detection.py (__all__: prior_box,
density_prior_box, multi_box_head, bipartite_match, target_assign,
detection_output, ssd_loss, rpn_target_assign, anchor_generator,
generate_proposals, iou_similarity, box_coder, polygon_box_transform,
yolov3_loss, yolo_box, box_clip, multiclass_nms,
distribute_fpn_proposals, box_decoder_and_assign,
collect_fpn_proposals; detection_map is provided host-side as
metrics.DetectionMAP).

LoD → padded redesign: ground-truth boxes arrive as dense [N, B, 4]
tensors with all-zero padding rows (and [N, B] labels), ROI lists carry
an explicit batch-index tensor, and NMS-style ops return padded outputs
plus valid counts — see ops/detection_ops.py for the rationale.
"""

from __future__ import annotations

from ..core.enforce import enforce
from ..layer_helper import LayerHelper

__all__ = [
    "prior_box", "density_prior_box", "multi_box_head",
    "bipartite_match", "target_assign", "detection_output", "ssd_loss",
    "rpn_target_assign", "anchor_generator", "generate_proposals",
    "iou_similarity", "box_coder", "polygon_box_transform",
    "yolov3_loss", "yolo_box", "box_clip", "multiclass_nms",
    "distribute_fpn_proposals", "box_decoder_and_assign",
    "collect_fpn_proposals", "roi_align", "roi_pool",
    "psroi_pool", "deformable_conv", "generate_proposal_labels",
    "generate_mask_labels"]


def _mk(helper, dtype="float32", stop_gradient=False):
    return helper.create_variable_for_type_inference(
        dtype, stop_gradient=stop_gradient)


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              name=None, min_max_aspect_ratios_order=False):
    """Reference: layers/detection.py prior_box."""
    helper = LayerHelper("prior_box", name=name)
    boxes = _mk(helper, stop_gradient=True)
    var = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="prior_box", inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"min_sizes": tuple(float(m) for m in min_sizes),
               "max_sizes": tuple(float(m) for m in (max_sizes or ())),
               "aspect_ratios": tuple(aspect_ratios),
               "variances": tuple(variance), "flip": flip,
               "clip": clip, "step_w": float(steps[0]),
               "step_h": float(steps[1]), "offset": offset,
               "min_max_aspect_ratios_order":
                   min_max_aspect_ratios_order})
    return boxes, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    helper = LayerHelper("density_prior_box", name=name)
    boxes = _mk(helper, stop_gradient=True)
    var = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={"densities": tuple(densities),
               "fixed_sizes": tuple(fixed_sizes),
               "fixed_ratios": tuple(fixed_ratios),
               "variances": tuple(variance), "clip": clip,
               "step_w": float(steps[0]), "step_h": float(steps[1]),
               "offset": offset, "flatten_to_2d": flatten_to_2d})
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None,
                     offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = _mk(helper, stop_gradient=True)
    var = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="anchor_generator", inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={"anchor_sizes": tuple(anchor_sizes or
                                     (64.0, 128.0, 256.0, 512.0)),
               "aspect_ratios": tuple(aspect_ratios or (0.5, 1.0, 2.0)),
               "variances": tuple(variance),
               "stride": tuple(stride or (16.0, 16.0)),
               "offset": offset})
    return anchors, var


def iou_similarity(x, y, box_normalized=True, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = _mk(helper, stop_gradient=True)
    helper.append_op(type="iou_similarity",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    helper = LayerHelper("box_coder", name=name)
    out = _mk(helper)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = tuple(float(v) for v in prior_box_var)
    elif prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": [out]}, attrs=attrs)
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = _mk(helper)
    helper.append_op(type="box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out = _mk(helper, stop_gradient=True)
    helper.append_op(type="polygon_box_transform",
                     inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """dist_matrix [B, N, M] (padded gt rows all-zero) →
    (match_indices [B, M] int32, match_distance [B, M])."""
    helper = LayerHelper("bipartite_match", name=name)
    midx = _mk(helper, "int32", stop_gradient=True)
    mdist = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="bipartite_match", inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [midx],
                 "ColToRowMatchDist": [mdist]},
        attrs={"match_type": match_type,
               "dist_threshold": dist_threshold})
    return midx, mdist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0.0, name=None):
    """input [B, N, K] entity targets; matched_indices [B, M];
    negative_indices is a [B, M] 0/1 mask (LoD redesign). The gather is
    differentiable through ``input`` (rpn_target_assign routes head
    predictions through it, which must carry gradient)."""
    helper = LayerHelper("target_assign", name=name)
    out = _mk(helper)
    weight = _mk(helper, stop_gradient=True)
    inputs = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        inputs["NegIndices"] = [negative_indices]
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": [out], "OutWeight": [weight]},
                     attrs={"mismatch_value": float(mismatch_value)})
    return out, weight


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None):
    """bboxes [N, M, 4], scores [N, C, M] → (Out [N, keep_top_k, 6]
    padded with -1 rows, valid counts [N])."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = _mk(helper, stop_gradient=True)
    num = _mk(helper, "int32", stop_gradient=True)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "NmsRoisNum": [num]},
        attrs={"background_label": background_label,
               "score_threshold": float(score_threshold),
               "nms_top_k": nms_top_k,
               "nms_threshold": float(nms_threshold),
               "nms_eta": float(nms_eta), "keep_top_k": keep_top_k,
               "normalized": normalized})
    return out, num


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3,
                     nms_top_k=400, keep_top_k=200,
                     score_threshold=0.01, nms_eta=1.0):
    """SSD inference head: softmax + decode + multiclass NMS
    (reference: layers/detection.py detection_output — which applies
    the softmax internally too). loc [N, P, 4], scores [N, P, C] raw
    logits, prior_box [P, 4]."""
    from . import nn
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores = nn.softmax(scores)
    scores_t = nn.transpose(scores, (0, 2, 1))  # [N, C, P]
    return multiclass_nms(decoded, scores_t,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          normalized=False, nms_eta=nms_eta,
                          background_label=background_label)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0,
             overlap_threshold=0.5, neg_pos_ratio=3.0, neg_overlap=0.5,
             loc_loss_weight=1.0, conf_loss_weight=1.0,
             match_type="per_prediction", mining_type="max_negative",
             normalize=True, sample_size=None):
    """Fused SSD multibox loss (see ops/detection_ops.py ssd_loss).
    gt_box [N, B, 4] padded (all-zero rows), gt_label [N, B] int.
    Returns [N, P] per-prior weighted loss."""
    helper = LayerHelper("ssd_loss")
    out = _mk(helper)
    inputs = {"Location": [location], "Confidence": [confidence],
              "GtBox": [gt_box], "GtLabel": [gt_label],
              "PriorBox": [prior_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="ssd_loss", inputs=inputs, outputs={"Loss": [out]},
        attrs={"background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio,
               "neg_overlap": neg_overlap,
               "loc_loss_weight": loc_loss_weight,
               "conf_loss_weight": conf_loss_weight,
               "match_type": match_type, "mining_type": mining_type,
               "normalize": normalize,
               "sample_size": int(sample_size or 0)})
    return out


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """gt_box [N, B, 4] (cx, cy, w, h normalized; zero rows pad)."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = _mk(helper)
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    helper.append_op(
        type="yolov3_loss", inputs=inputs, outputs={"Loss": [loss]},
        attrs={"anchors": tuple(anchors),
               "anchor_mask": tuple(anchor_mask),
               "class_num": class_num, "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio,
               "use_label_smooth": use_label_smooth})
    return loss


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    helper = LayerHelper("yolo_box", name=name)
    boxes = _mk(helper, stop_gradient=True)
    scores = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="yolo_box", inputs={"X": [x], "ImgSize": [img_size]},
        outputs={"Boxes": [boxes], "Scores": [scores]},
        attrs={"anchors": tuple(anchors), "class_num": class_num,
               "conf_thresh": conf_thresh,
               "downsample_ratio": downsample_ratio,
               "clip_bbox": clip_bbox})
    return boxes, scores


def generate_proposals(scores, bbox_deltas, im_info, anchors,
                       variances, pre_nms_top_n=6000,
                       post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, name=None):
    """Returns (rpn_rois [N, post_nms_top_n, 4] padded, roi_probs,
    rois_num [N]) — the LoD output of the reference becomes
    padded + count."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = _mk(helper, stop_gradient=True)
    probs = _mk(helper, stop_gradient=True)
    num = _mk(helper, "int32", stop_gradient=True)
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                 "RpnRoisNum": [num]},
        attrs={"pre_nms_top_n": pre_nms_top_n,
               "post_nms_top_n": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size,
               "eta": eta})
    return rois, probs, num


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """Static redesign: returns fixed-size [N, S] slot tensors
    (anchor indices padded with -1, labels 1/0/-1, encoded target
    boxes, inside weights) plus the predictions gathered per slot.
    Reference returns ragged sampled subsets; see
    ops/detection_ops.py rpn_target_assign."""
    helper = LayerHelper("rpn_target_assign")
    loc_idx = _mk(helper, "int32", stop_gradient=True)
    score_idx = _mk(helper, "int32", stop_gradient=True)
    tgt_lbl = _mk(helper, "int32", stop_gradient=True)
    tgt_bbox = _mk(helper, stop_gradient=True)
    bbox_w = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="rpn_target_assign",
        inputs={"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
                "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        outputs={"LocationIndex": [loc_idx], "ScoreIndex": [score_idx],
                 "TargetLabel": [tgt_lbl], "TargetBBox": [tgt_bbox],
                 "BBoxInsideWeight": [bbox_w]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "use_random": use_random})
    # gather sampled predictions per slot ([N, S, ...]) by reusing the
    # target_assign gather (differentiable through the predictions;
    # indices < 0 → 0-filled padding slots)
    pred_loc, _ = target_assign(bbox_pred, loc_idx)
    pred_score, _ = target_assign(cls_logits, score_idx)
    return pred_score, pred_loc, tgt_lbl, tgt_bbox, bbox_w


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd,
                             gt_boxes, im_info,
                             batch_size_per_im=256, fg_fraction=0.25,
                             fg_thresh=0.25, bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """Second-stage RoI sampling for Fast/Mask-RCNN training
    (reference: layers/detection.py generate_proposal_labels ->
    generate_proposal_labels_op.cc). Padded [N, S] outputs; pad slots
    carry label -1 (see ops/detection_ops.py)."""
    enforce(class_nums is not None,
            "generate_proposal_labels needs class_nums (the number of "
            "detection classes incl. background, e.g. 81 for COCO) to "
            "size its per-class bbox targets")
    helper = LayerHelper("generate_proposal_labels")
    rois = _mk(helper, stop_gradient=True)
    labels = _mk(helper, "int32", stop_gradient=True)
    tgts = _mk(helper, stop_gradient=True)
    iw = _mk(helper, stop_gradient=True)
    ow = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="generate_proposal_labels",
        inputs={"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
                "ImInfo": [im_info]},
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [tgts], "BboxInsideWeights": [iw],
                 "BboxOutsideWeights": [ow]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi,
               "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": tuple(bbox_reg_weights),
               "class_nums": int(class_nums), "use_random": use_random})
    return rois, labels, tgts, iw, ow


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_masks,
                         rois, labels_int32, num_classes, resolution):
    """Mask-head targets (reference: layers/detection.py
    generate_mask_labels -> generate_mask_labels_op.cc). TPU redesign
    consumes rasterized GtMasks [N, B, H, W] instead of LoD polygon
    lists; see ops/detection_ops.py generate_mask_labels."""
    helper = LayerHelper("generate_mask_labels")
    mask_rois = _mk(helper, stop_gradient=True)
    has_mask = _mk(helper, "int32", stop_gradient=True)
    mask_t = _mk(helper, "int32", stop_gradient=True)
    helper.append_op(
        type="generate_mask_labels",
        inputs={"ImInfo": [im_info], "GtClasses": [gt_classes],
                "IsCrowd": [is_crowd], "GtMasks": [gt_masks],
                "Rois": [rois], "LabelsInt32": [labels_int32]},
        outputs={"MaskRois": [mask_rois],
                 "RoiHasMaskInt32": [has_mask],
                 "MaskInt32": [mask_t]},
        attrs={"num_classes": int(num_classes),
               "resolution": int(resolution)})
    return mask_rois, has_mask, mask_t


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip=4.135166556742356,
                           name=None):
    helper = LayerHelper("box_decoder_and_assign", name=name)
    dec = _mk(helper, stop_gradient=True)
    assign = _mk(helper, stop_gradient=True)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box],
              "BoxScore": [box_score]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_decoder_and_assign", inputs=inputs,
        outputs={"DecodeBox": [dec], "OutputAssignBox": [assign]},
        attrs={"box_clip": box_clip})
    return dec, assign


def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale, name=None):
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_levels = max_level - min_level + 1
    outs = [_mk(helper, stop_gradient=True) for _ in range(n_levels)]
    restore = _mk(helper, "int32", stop_gradient=True)
    helper.append_op(
        type="distribute_fpn_proposals",
        inputs={"FpnRois": [fpn_rois]},
        outputs={"MultiFpnRois": outs, "RestoreIndex": [restore]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale})
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level,
                          max_level, post_nms_top_n, name=None):
    helper = LayerHelper("collect_fpn_proposals", name=name)
    out = _mk(helper, stop_gradient=True)
    helper.append_op(
        type="collect_fpn_proposals",
        inputs={"MultiLevelRois": list(multi_rois),
                "MultiLevelScores": list(multi_scores)},
        outputs={"FpnRois": [out]},
        attrs={"post_nms_topN": post_nms_top_n})
    return out


def roi_align(input, rois, rois_batch_idx, pooled_height=1,
              pooled_width=1, spatial_scale=1.0, sampling_ratio=-1,
              name=None):
    """rois [R, 4] + rois_batch_idx [R] int32 (the LoD redesign;
    reference roi_align_op.cc infers the batch from LoD)."""
    helper = LayerHelper("roi_align", name=name)
    out = _mk(helper)
    helper.append_op(
        type="roi_align",
        inputs={"X": [input], "ROIs": [rois],
                "RoisBatchIdx": [rois_batch_idx]},
        outputs={"Out": [out]},
        attrs={"pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "spatial_scale": spatial_scale,
               "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, rois_batch_idx, pooled_height=1,
             pooled_width=1, spatial_scale=1.0, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = _mk(helper)
    argmax = _mk(helper, "int32", stop_gradient=True)
    helper.append_op(
        type="roi_pool",
        inputs={"X": [input], "ROIs": [rois],
                "RoisBatchIdx": [rois_batch_idx]},
        outputs={"Out": [out], "Argmax": [argmax]},
        attrs={"pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "spatial_scale": spatial_scale})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multibox head (reference: layers/detection.py
    multi_box_head): per feature map, generate priors and conv
    loc/conf predictions; concat across maps. Returns
    (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes [P, 4],
    variances [P, 4])."""
    from . import nn, tensor

    n_maps = len(inputs)
    if min_sizes is None:
        # reference's ratio interpolation (detection.py multi_box_head)
        min_sizes, max_sizes = [], []
        step = int(
            (max_ratio - min_ratio) // max(n_maps - 2, 1)) if \
            min_ratio is not None else 0
        min_sizes = [base_size * 0.1]
        max_sizes = [base_size * 0.2]
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = min_sizes[:n_maps]
        max_sizes = max_sizes[:n_maps]

    locs, confs, prior_list, var_list = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        mxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        st = steps[i] if steps else [
            step_w[i] if step_w else 0.0,
            step_h[i] if step_h else 0.0]
        if not isinstance(st, (list, tuple)):
            st = [st, st]
        box, var = prior_box(
            feat, image, [ms] if not isinstance(ms, (list, tuple))
            else ms,
            [mxs] if mxs and not isinstance(mxs, (list, tuple))
            else mxs, ar, variance, flip, clip, st, offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # priors per cell comes from the generated boxes themselves
        # ([H, W, P, 4] — shape inference ran at append_op), so the
        # conv head channel count can never disagree with the priors
        # (reference reads it off the prior op output the same way)
        num_priors = box.shape[2]

        loc = nn.conv2d(feat, num_priors * 4, kernel_size,
                        padding=pad, stride=stride)
        conf = nn.conv2d(feat, num_priors * num_classes, kernel_size,
                         padding=pad, stride=stride)
        # NCHW → [N, H*W*priors, 4/C]
        loc = nn.transpose(loc, (0, 2, 3, 1))
        loc = nn.reshape(loc, (0, -1, 4))
        conf = nn.transpose(conf, (0, 2, 3, 1))
        conf = nn.reshape(conf, (0, -1, num_classes))
        locs.append(loc)
        confs.append(conf)
        prior_list.append(nn.reshape(box, (-1, 4)))
        var_list.append(nn.reshape(var, (-1, 4)))

    mbox_locs = tensor.concat(locs, axis=1)
    mbox_confs = tensor.concat(confs, axis=1)
    boxes = tensor.concat(prior_list, axis=0)
    variances = tensor.concat(var_list, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def psroi_pool(input, rois, output_channels, spatial_scale,
               pooled_height, pooled_width, rois_batch_idx=None,
               name=None):
    """Position-sensitive ROI pooling (reference: layers/detection.py?
    -> psroi_pool_op.cc; R-FCN heads)."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if rois_batch_idx is None:
        from . import tensor as _t
        rois_batch_idx = _t.fill_constant_batch_size_like(
            rois, shape=[-1], dtype="int32", value=0)
    helper.append_op(
        type="psroi_pool",
        inputs={"X": [input], "ROIs": [rois],
                "RoisBatchIdx": [rois_batch_idx]},
        outputs={"Out": [out]},
        attrs={"output_channels": output_channels,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "spatial_scale": spatial_scale})
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=64,
                    param_attr=None, bias_attr=None, name=None):
    """Deformable convolution layer (reference: layers/nn.py
    deformable_conv -> deformable_conv_op.cc)."""
    from ..core.shape_utils import pair as _pair
    from ..layer_helper import LayerHelper
    helper = LayerHelper("deformable_conv", name=name)

    fsize = _pair(filter_size)
    channels = input.shape[1]
    w = helper.create_parameter(
        attr=param_attr,
        shape=(num_filters, channels // groups) + fsize,
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="deformable_conv",
        inputs={"Input": [input], "Offset": [offset],
                "Mask": [mask] if mask is not None else [],
                "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _pair(stride), "paddings": _pair(padding),
               "dilations": _pair(dilation), "groups": groups,
               "deformable_groups": deformable_groups,
               "im2col_step": im2col_step})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=(num_filters,),
                                    dtype=input.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=1)
    return out
