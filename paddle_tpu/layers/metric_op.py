"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""

from __future__ import annotations

from .. import unique_name
from ..layer_helper import LayerHelper
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """Reference: metric_op.py accuracy -> accuracy_op.cc."""
    helper = LayerHelper("accuracy")
    topk_out, topk_idx = nn.topk(input, k=k)
    acc = helper.create_variable_for_type_inference("float32",
                                                    stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    helper.append_op(type="accuracy",
                     inputs={"Out": [topk_out], "Indices": [topk_idx],
                             "Label": [label]},
                     outputs={"Accuracy": [acc], "Correct": [correct],
                              "Total": [total]})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC with persistable bucket state (reference:
    metric_op.py auc -> auc_op.cc)."""
    from .tensor import create_global_var
    helper = LayerHelper("auc")
    stat_pos = create_global_var((num_thresholds + 1,), 0.0, "float32",
                                 persistable=True)
    stat_neg = create_global_var((num_thresholds + 1,), 0.0, "float32",
                                 persistable=True)
    auc_out = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    helper.append_op(type="auc",
                     inputs={"Predict": [input], "Label": [label],
                             "StatPos": [stat_pos],
                             "StatNeg": [stat_neg]},
                     outputs={"AUC": [auc_out],
                              "StatPosOut": [stat_pos],
                              "StatNegOut": [stat_neg]},
                     attrs={"num_thresholds": num_thresholds})
    return auc_out, auc_out, [stat_pos, stat_neg]


def _extract_chunks(tags, length, scheme, num_chunk_types,
                    excluded_chunk_types):
    """conlleval-style chunk extraction from an id-encoded tag row
    (reference: operators/chunk_eval_op.h Segment extraction).
    Encoding follows the reference: IOB tag = type*2 + {0:B, 1:I};
    IOE type*2 + {0:I, 1:E}; IOBES type*4 + {0:B,1:I,2:E,3:S};
    ``plain`` = the tag IS the type. The id num_chunk_types*K (one
    past the last) is the outside/O tag."""
    chunks = []
    n_tag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    o_tag = num_chunk_types * n_tag
    start = None
    cur_type = None

    def close(end):
        nonlocal start, cur_type
        if start is not None and \
                cur_type not in (excluded_chunk_types or ()):
            chunks.append((start, end, cur_type))
        start, cur_type = None, None

    for i in range(int(length)):
        t = int(tags[i])
        if t >= o_tag or t < 0:
            close(i - 1)
            continue
        typ, pos = divmod(t, n_tag)
        if scheme == "plain":
            is_begin = cur_type != typ or start is None
            is_end = False
        elif scheme == "IOB":
            is_begin = pos == 0 or cur_type != typ
            is_end = False
        elif scheme == "IOE":
            is_begin = cur_type != typ or start is None
            is_end = pos == 1
        else:  # IOBES
            is_begin = pos in (0, 3) or cur_type != typ
            is_end = pos in (2, 3)
        if is_begin:
            close(i - 1)
            start, cur_type = i, typ
        if is_end:
            close(i)
    close(int(length) - 1)
    return set(chunks)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """In-graph chunking metrics (reference: layers/nn.py chunk_eval ->
    operators/chunk_eval_op.cc). TPU-native: the irregular chunk walk
    runs as a host callback (py_func machinery) — metric ops are not on
    the step's critical path. Inputs are padded [N, S] tag ids with a
    lengths vector; returns (precision, recall, f1, num_infer_chunks,
    num_label_chunks, num_correct_chunks)."""
    helper = LayerHelper("chunk_eval")
    excl = tuple(excluded_chunk_types or ())

    def _compute(inf, lab, lens):
        import numpy as _np
        n_inf = n_lab = n_cor = 0
        for row in range(inf.shape[0]):
            ln = int(lens[row]) if lens is not None else inf.shape[1]
            ci = _extract_chunks(inf[row], ln, chunk_scheme,
                                 num_chunk_types, excl)
            cl = _extract_chunks(lab[row], ln, chunk_scheme,
                                 num_chunk_types, excl)
            n_inf += len(ci)
            n_lab += len(cl)
            n_cor += len(ci & cl)
        p = n_cor / n_inf if n_inf else 0.0
        r = n_cor / n_lab if n_lab else 0.0
        f1 = 2 * p * r / (p + r) if n_cor else 0.0
        return (_np.float32(p), _np.float32(r), _np.float32(f1),
                _np.int32(n_inf), _np.int32(n_lab), _np.int32(n_cor))

    outs = [helper.main_program.current_block().create_var(
        name=unique_name.generate("chunk_eval_%d" % i),
        shape=(), dtype=dt, stop_gradient=True)
        for i, dt in enumerate(["float32", "float32", "float32",
                                "int32", "int32", "int32"])]
    xs = [input, label]
    if seq_length is not None:
        xs.append(seq_length)

        def fn(inf, lab, lens):
            return _compute(inf, lab, lens)
    else:
        def fn(inf, lab):
            return _compute(inf, lab, None)

    nn.py_func(fn, xs, outs)
    return tuple(outs)
