"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """Reference: metric_op.py accuracy -> accuracy_op.cc."""
    helper = LayerHelper("accuracy")
    topk_out, topk_idx = nn.topk(input, k=k)
    acc = helper.create_variable_for_type_inference("float32",
                                                    stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    helper.append_op(type="accuracy",
                     inputs={"Out": [topk_out], "Indices": [topk_idx],
                             "Label": [label]},
                     outputs={"Accuracy": [acc], "Correct": [correct],
                              "Total": [total]})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC with persistable bucket state (reference:
    metric_op.py auc -> auc_op.cc)."""
    from .tensor import create_global_var
    helper = LayerHelper("auc")
    stat_pos = create_global_var((num_thresholds + 1,), 0.0, "float32",
                                 persistable=True)
    stat_neg = create_global_var((num_thresholds + 1,), 0.0, "float32",
                                 persistable=True)
    auc_out = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    helper.append_op(type="auc",
                     inputs={"Predict": [input], "Label": [label],
                             "StatPos": [stat_pos],
                             "StatNeg": [stat_neg]},
                     outputs={"AUC": [auc_out],
                              "StatPosOut": [stat_pos],
                              "StatNegOut": [stat_neg]},
                     attrs={"num_thresholds": num_thresholds})
    return auc_out, auc_out, [stat_pos, stat_neg]
