"""Control-flow and comparison layers (reference:
python/paddle/fluid/layers/control_flow.py — less_than:1297, equal,
array ops :947, While:697, IfElse:1553, Switch:1264, StaticRNN:406,
DynamicRNN:1815).

TPU-native redesign of the structured constructs:
  - ``StaticRNN`` / ``DynamicRNN`` record their step sub-block and lower
    through ONE ``lax.scan`` (ops/control_flow_ops.py) — compiled,
    differentiable, masked for variable lengths (replaces the
    reference's while+tensor-array recurrent machinery and LoD
    reordering).
  - ``While`` + tensor arrays keep full fluid dynamism and run in the
    Executor's interpreted (eager) mode.
  - ``IfElse`` / ``Switch`` compute all branches and merge with
    ``where`` selects — both sides of a branch are cheap relative to a
    TPU divergent-control-flow stall, and the program stays one static
    XLA computation.
"""

from __future__ import annotations

import contextlib

from .. import framework
from ..core.enforce import InvalidArgumentError, enforce
from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = ["less_than", "less_equal", "greater_than", "greater_equal",
           "equal", "not_equal", "logical_and", "logical_or",
           "logical_xor", "logical_not", "is_empty", "While",
           "StaticRNN", "DynamicRNN", "IfElse", "Switch", "create_array",
           "array_write", "array_read", "array_length", "Print"]


def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
        cond.stop_gradient = True
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def logical_and(x, y, out=None):
    return _cmp("logical_and", x, y, out)


def logical_or(x, y, out=None):
    return _cmp("logical_or", x, y, out)


def logical_xor(x, y, out=None):
    return _cmp("logical_xor", x, y, out)


def logical_not(x, out=None):
    helper = LayerHelper("logical_not")
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
        out.stop_gradient = True
    helper.append_op(type="logical_not", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond


# ---------------------------------------------------------------------------
# tensor arrays (reference: control_flow.py array_write:947, array_read,
# array_length; eager mode only — see ops/control_flow_ops.py)
# ---------------------------------------------------------------------------

def create_array(dtype="float32"):
    helper = LayerHelper("create_array")
    out = helper.create_variable_for_type_inference(dtype)
    out.stop_gradient = True
    helper.append_op(type="create_array", outputs={"Out": [out]},
                     attrs={"dtype": dtype})
    return out


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    inputs = {"X": [x], "I": [i], "Array": [array]}
    helper.append_op(type="array_write", inputs=inputs,
                     outputs={"Out": [array]})
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type="array_read",
                     inputs={"Array": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int64")
    out.stop_gradient = True
    helper.append_op(type="array_length", inputs={"Array": [array]},
                     outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# block-analysis helpers shared by While / StaticRNN / DynamicRNN
# ---------------------------------------------------------------------------

def _read_written(sub_block):
    """Names read from / written to by a sub-block's ops, split into
    block-local vs parent-visible (a name created inside the sub-block
    is local; anything else resolves up the parent chain)."""
    read, written = [], []
    seen_r, seen_w = set(), set()
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n not in seen_r:
                seen_r.add(n)
                read.append(n)
        for n in op.output_arg_names:
            if n not in seen_w:
                seen_w.add(n)
                written.append(n)
    outer_read = [n for n in read if n not in sub_block.vars]
    outer_written = [n for n in written if n not in sub_block.vars]
    return outer_read, outer_written


class _SubBlockGuard:
    """Enter a fresh sub-block of the main program; on enter hand the new
    block to ``on_enter``, on exit the finished block to ``on_exit``."""

    def __init__(self, on_exit, on_enter=None):
        self._on_exit = on_exit
        self._on_enter = on_enter

    def __enter__(self):
        main = framework.default_main_program()
        self.block = main._create_block()
        if self._on_enter is not None:
            self._on_enter(self.block)
        return self.block

    def __exit__(self, exc_type, exc_val, exc_tb):
        main = framework.default_main_program()
        main._rollback()
        if exc_type is None:
            self._on_exit(self.block)
        return False


# ---------------------------------------------------------------------------
# While (reference: control_flow.py:697)
# ---------------------------------------------------------------------------

class While:
    """``while cond:`` over a sub-block. The condition must be a bool
    Variable of one element that the body re-writes (e.g. via
    ``layers.less_than(i, n, cond=cond)``).

    Compilation (reference: while_op.cc + while_grad):
      - plain body        -> ``lax.while_loop`` (XLA While HLO): jitted,
        data-dependent trip count, forward-only;
      - ``max_iters`` set -> ``lax.scan`` over the bound with a
        done-mask: jitted AND reverse-mode differentiable — training
        through the loop works (``append_backward`` emits a generic
        vjp op like any other differentiable op);
      - body using tensor arrays -> eager interpreted mode (full
        dynamism: growing arrays, concrete indices).
    For fixed-length recurrence prefer StaticRNN/DynamicRNN.
    """

    def __init__(self, cond, is_test=False, name=None, max_iters=None):
        enforce(isinstance(cond, Variable), "While cond must be a Variable")
        enforce(cond.dtype == "bool", "While cond must be bool, got %s"
                % cond.dtype)
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.is_test = is_test
        self.max_iters = int(max_iters) if max_iters else 0

    def block(self):
        return _SubBlockGuard(self._complete)

    def _complete(self, sub_block):
        cond_name = self.cond_var.name
        outer_read, outer_written = _read_written(sub_block)
        enforce(cond_name in outer_written,
                "While body never updates the loop condition %r — the "
                "loop would not terminate" % cond_name)
        # carried outputs need an initial value, so they are inputs too
        in_names = list(dict.fromkeys(
            outer_read + [n for n in outer_written if n != cond_name]))
        in_names = [n for n in in_names if n != cond_name]
        out_names = [n for n in outer_written if n != cond_name]
        parent = sub_block.parent_block
        in_vars = [parent._find_var_recursive(n) for n in in_names]
        enforce(all(v is not None for v in in_vars),
                "While body reads undeclared variables")
        parent.append_op(
            type="while",
            inputs={"Condition": [cond_name], "X": in_names},
            outputs={"Out": out_names + [cond_name]},
            attrs={"sub_block": sub_block.idx,
                   "in_names": tuple(in_names),
                   "out_names": tuple(out_names + [cond_name]),
                   "cond_name": cond_name,
                   "is_test": self.is_test,
                   "max_iters": self.max_iters})


# ---------------------------------------------------------------------------
# StaticRNN (reference: control_flow.py:406) — fixed-length, time-major
# ---------------------------------------------------------------------------

class StaticRNN:
    """Fixed-length recurrence over time-major inputs ``[T, batch, ...]``,
    lowered to one ``lax.scan``::

        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)               # [batch, d]
            h_prev = rnn.memory(init=h0)          # carried state
            h = layers.fc(input=[x_t, h_prev], size=d, act="tanh")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()                               # [T, batch, d]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._in_step = False
        self._sub_block = None
        self._step_inputs = []   # (parent var, sub var)
        self._memories = []      # [init var, pre var, new var]
        self._step_outputs = []  # sub vars
        self._outputs = []       # parent vars (stacked)
        self.seq_len = None

    # -- step context ------------------------------------------------------
    def step(self):
        def on_enter(block):
            self._in_step = True
            self._sub_block = block

        return _SubBlockGuard(self._complete, on_enter)

    def _require_in_step(self):
        enforce(self._in_step and self._sub_block is not None,
                "call inside `with rnn.step():`")

    # -- recording API -----------------------------------------------------
    def step_input(self, x):
        self._require_in_step()
        enforce(len(x.shape) >= 2 or -1 in x.shape,
                "step_input needs [T, batch, ...] input")
        if self.seq_len is None:
            self.seq_len = x.shape[0]
        ivar = self._sub_block.create_var(
            name=framework.unique_name.generate(self.helper.name + ".in"),
            shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._step_inputs.append((x, ivar))
        return ivar

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1,
               dtype="float32"):
        self._require_in_step()
        if init is None:
            enforce(shape is not None and batch_ref is not None,
                    "memory needs either init= or (shape=, batch_ref=)")
            # build the boot memory in the PARENT block; if batch_ref is
            # a step-input slice ([batch, ...]) swap in its parent
            # sequence var, whose batch dim sits one axis later (after
            # the time axis)
            for pv, iv in self._step_inputs:
                if batch_ref is iv or batch_ref.name == iv.name:
                    batch_ref = pv
                    ref_batch_dim_idx += 1
                    break
            # resolve a -1 batch dim from the reference when it's
            # static — keeps downstream shape inference concrete
            shape = list(shape)
            if (shape[init_batch_dim_idx] == -1
                    and len(batch_ref.shape) > ref_batch_dim_idx
                    and batch_ref.shape[ref_batch_dim_idx] != -1):
                shape[init_batch_dim_idx] = \
                    batch_ref.shape[ref_batch_dim_idx]
            parent = self._sub_block.parent_block
            init = parent.create_var(
                name=framework.unique_name.generate(
                    self.helper.name + ".mem_init"),
                shape=tuple(shape), dtype=dtype, stop_gradient=True)
            parent.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [batch_ref]},
                outputs={"Out": [init]},
                attrs={"shape": tuple(shape), "dtype": dtype,
                       "value": float(init_value),
                       "input_dim_idx": ref_batch_dim_idx,
                       "output_dim_idx": init_batch_dim_idx})
        pre = self._sub_block.create_var(
            name=framework.unique_name.generate(self.helper.name + ".mem"),
            shape=tuple(init.shape), dtype=init.dtype)
        self._memories.append([init, pre, None])
        return pre

    def update_memory(self, mem, var):
        self._require_in_step()
        for rec in self._memories:
            if rec[1] is mem or rec[1].name == mem.name:
                rec[2] = var
                return
        raise InvalidArgumentError("update_memory: %r is not a memory "
                                   "of this StaticRNN" % mem.name)

    def step_output(self, o):
        self._require_in_step()
        self._step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # -- completion --------------------------------------------------------
    def _complete(self, sub_block):
        self._in_step = False
        enforce(self._step_inputs, "StaticRNN needs a step_input")
        enforce(self._step_outputs, "StaticRNN needs a step_output")
        for rec in self._memories:
            enforce(rec[2] is not None,
                    "memory %r never updated (call update_memory)"
                    % rec[1].name)
        parent = sub_block.parent_block
        outer_read, _w = _read_written(sub_block)
        consumed = ({v.name for v, _ in self._step_inputs} |
                    {rec[0].name for rec in self._memories})
        outer_names = [n for n in outer_read if n not in consumed]

        T = self.seq_len
        outs = []
        for o in self._step_outputs:
            out = parent.create_var(
                name=framework.unique_name.generate(
                    self.helper.name + ".out"),
                shape=(T,) + tuple(o.shape), dtype=o.dtype)
            outs.append(out)
        last_mems = []
        for rec in self._memories:
            lm = parent.create_var(
                name=framework.unique_name.generate(
                    self.helper.name + ".last"),
                shape=tuple(rec[1].shape), dtype=rec[1].dtype)
            last_mems.append(lm)
        self._outputs = outs
        self._last_mems = last_mems

        parent.append_op(
            type="static_rnn",
            inputs={"StepIn": [v.name for v, _ in self._step_inputs],
                    "Init": [rec[0].name for rec in self._memories],
                    "X": outer_names},
            outputs={"Out": [o.name for o in outs],
                     "LastMem": [m.name for m in last_mems]},
            attrs={"sub_block": sub_block.idx,
                   "step_in_names": tuple(i.name for _, i in
                                          self._step_inputs),
                   "mem_pre_names": tuple(rec[1].name
                                          for rec in self._memories),
                   "mem_new_names": tuple(rec[2].name
                                          for rec in self._memories),
                   "out_names": tuple(o.name for o in self._step_outputs),
                   "outer_names": tuple(outer_names)})

    def __call__(self):
        enforce(self._outputs, "StaticRNN not completed")
        if len(self._outputs) == 1:
            return self._outputs[0]
        return list(self._outputs)


# ---------------------------------------------------------------------------
# DynamicRNN (reference: control_flow.py:1815) — batch-major padded
# sequences + explicit lengths (the padded+mask replacement for LoD)
# ---------------------------------------------------------------------------

class DynamicRNN:
    """Variable-length recurrence over batch-major padded input
    ``[batch, max_len, ...]`` with a per-example lengths vector::

        drnn = layers.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, lengths=seq_len)   # [batch, d]
            h_prev = drnn.memory(shape=[hid], value=0.0)
            h = layers.fc(input=[x_t, h_prev], size=hid, act="relu")
            drnn.update_memory(h_prev, h)
            drnn.output(h)
        out = drnn()            # [batch, max_len, hid], zeros past length

    Steps beyond an example's length neither update its memories nor
    emit output (masked in the scan body), matching the reference's
    LoD-driven early exit without dynamic shapes.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._rnn = StaticRNN(name=name)
        self._lengths = None

    def block(self):
        def on_enter(block):
            self._rnn._in_step = True
            self._rnn._sub_block = block

        return _SubBlockGuard(self._complete, on_enter)

    def step_input(self, x, level=0, lengths=None):
        self._rnn._require_in_step()
        enforce(len(x.shape) >= 2 or -1 in x.shape,
                "step_input needs [batch, max_len, ...] input")
        if self._rnn.seq_len is None:
            self._rnn.seq_len = x.shape[1]
        if lengths is not None:
            self._lengths = lengths
        ivar = self._rnn._sub_block.create_var(
            name=framework.unique_name.generate(self.helper.name + ".in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._rnn._step_inputs.append((x, ivar))
        return ivar

    def static_input(self, x):
        return x  # non-stepped inputs are closed over from the outer block

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        if init is not None:
            return self._rnn.memory(init=init)
        enforce(self._rnn._step_inputs,
                "call step_input before memory(shape=...) so the batch "
                "size is known")
        batch_ref = self._rnn._step_inputs[0][0]
        return self._rnn.memory(shape=[-1] + list(shape),
                                batch_ref=batch_ref, init_value=value,
                                init_batch_dim_idx=0, ref_batch_dim_idx=0,
                                dtype=dtype)

    def update_memory(self, mem, var):
        self._rnn.update_memory(mem, var)

    def output(self, *outputs):
        for o in outputs:
            self._rnn.step_output(o)

    def _complete(self, sub_block):
        rnn = self._rnn
        rnn._in_step = False
        enforce(rnn._step_inputs, "DynamicRNN needs a step_input")
        enforce(rnn._step_outputs, "DynamicRNN needs an output")
        for rec in rnn._memories:
            enforce(rec[2] is not None,
                    "memory %r never updated" % rec[1].name)
        parent = sub_block.parent_block
        outer_read, _w = _read_written(sub_block)
        consumed = ({v.name for v, _ in rnn._step_inputs} |
                    {rec[0].name for rec in rnn._memories})
        if self._lengths is not None:
            consumed.add(self._lengths.name)
        outer_names = [n for n in outer_read if n not in consumed]

        T = rnn.seq_len
        outs = []
        for o in rnn._step_outputs:
            B = o.shape[0] if o.shape else -1
            out = parent.create_var(
                name=framework.unique_name.generate(
                    self.helper.name + ".out"),
                shape=(B, T) + tuple(o.shape[1:]), dtype=o.dtype)
            outs.append(out)
        last_mems = []
        for rec in rnn._memories:
            lm = parent.create_var(
                name=framework.unique_name.generate(
                    self.helper.name + ".last"),
                shape=tuple(rec[1].shape), dtype=rec[1].dtype)
            last_mems.append(lm)
        rnn._outputs = outs
        rnn._last_mems = last_mems

        inputs = {"StepIn": [v.name for v, _ in rnn._step_inputs],
                  "Init": [rec[0].name for rec in rnn._memories],
                  "X": outer_names}
        if self._lengths is not None:
            inputs["SeqLen"] = [self._lengths.name]
        parent.append_op(
            type="dynamic_rnn",
            inputs=inputs,
            outputs={"Out": [o.name for o in outs],
                     "LastMem": [m.name for m in last_mems]},
            attrs={"sub_block": sub_block.idx,
                   "step_in_names": tuple(i.name for _, i in
                                          rnn._step_inputs),
                   "mem_pre_names": tuple(rec[1].name
                                          for rec in rnn._memories),
                   "mem_new_names": tuple(rec[2].name
                                          for rec in rnn._memories),
                   "out_names": tuple(o.name for o in rnn._step_outputs),
                   "outer_names": tuple(outer_names)})

    def __call__(self):
        return self._rnn()


# ---------------------------------------------------------------------------
# IfElse (reference: control_flow.py:1553) — per-example branch, merged
# with where-selects (both branches computed; static XLA graph)
# ---------------------------------------------------------------------------

class IfElse:
    IN_IF_ELSE_TRUE_BLOCKS = 0
    IN_IF_ELSE_FALSE_BLOCKS = 1

    def __init__(self, cond, name=None):
        enforce(isinstance(cond, Variable), "IfElse cond must be a Variable")
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._true_outs = []
        self._false_outs = []
        self._current = None

    @contextlib.contextmanager
    def true_block(self):
        self._current = self._true_outs
        try:
            yield
        finally:
            self._current = None

    @contextlib.contextmanager
    def false_block(self):
        self._current = self._false_outs
        try:
            yield
        finally:
            self._current = None

    def input(self, x):
        enforce(self._current is not None,
                "IfElse.input() must be called inside a branch block")
        return x

    def output(self, *outs):
        enforce(self._current is not None,
                "IfElse.output() must be called inside a branch block")
        self._current.extend(outs)

    def __call__(self):
        enforce(len(self._true_outs) == len(self._false_outs),
                "IfElse branches produced %d vs %d outputs"
                % (len(self._true_outs), len(self._false_outs)))
        enforce(self._true_outs, "IfElse produced no outputs")
        merged = []
        for t, f in zip(self._true_outs, self._false_outs):
            out = self.helper.create_variable_for_type_inference(t.dtype)
            self.helper.append_op(
                type="where", inputs={"Condition": [self.cond],
                                      "X": [t], "Y": [f]},
                outputs={"Out": [out]})
            merged.append(out)
        if len(merged) == 1:
            return merged[0]
        return merged


# ---------------------------------------------------------------------------
# Switch (reference: control_flow.py:1264) — first-true-case-wins scalar
# branching, used by LR schedules; lowered to a nested where chain
# ---------------------------------------------------------------------------

class Switch:
    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._cases = []           # (cond var or None, [(var, temp)])
        self._inside = False

    def __enter__(self):
        self._inside = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self._merge()
        self._inside = False
        return False

    @contextlib.contextmanager
    def _case_guard(self, cond):
        enforce(self._inside, "Switch.case used outside `with Switch()`")
        block = self.helper.main_program.current_block()
        start = len(block.ops)
        preexisting = set(block.vars)
        b = block.parent_block
        while b is not None:
            preexisting.update(b.vars)
            b = b.parent_block
        yield
        end = len(block.ops)
        # Redirect writes to *pre-existing* vars into fresh temps so
        # cases don't clobber each other before the merge; vars created
        # inside the case are case-local and stay as-is.
        writes = {}
        for op in block.ops[start:end]:
            # inputs first: a read-modify-write op (increment) must read
            # the value written by the *previous* op of this case, not
            # the temp this op is about to define
            for slot, names in op.inputs.items():
                op.inputs[slot] = [writes.get(n, n) for n in names]
            for slot, names in op.outputs.items():
                new_names = []
                for n in names:
                    if n in preexisting:
                        if n not in writes:
                            v = block._find_var_recursive(n)
                            tmp = block.create_var(
                                name=framework.unique_name.generate(
                                    self.helper.name + ".case"),
                                shape=tuple(v.shape)
                                if v is not None else (),
                                dtype=v.dtype
                                if v is not None else "float32")
                            writes[n] = tmp.name
                        new_names.append(writes[n])
                    else:
                        new_names.append(n)
                op.outputs[slot] = new_names
        self._cases.append((cond, writes))

    def case(self, condition):
        return self._case_guard(condition)

    def default(self):
        return self._case_guard(None)

    def _new_bool(self, block, like):
        return block.create_var(
            name=framework.unique_name.generate(
                self.helper.name + ".cond"),
            shape=tuple(like.shape), dtype="bool")

    def _effective_conds(self, block):
        """First-true-wins across ALL cases (reference Switch executes
        exactly the first block whose condition holds,
        control_flow.py:1264): case i fires iff cond_i AND NOT any
        earlier cond; the default fires iff NO cond fired — regardless
        of which variables each case writes."""
        effs = []
        any_prev = None  # var name: OR of conds seen so far
        for cond, _writes in self._cases:
            if cond is None:
                effs.append(None)  # patched below with NOT any_prev
                continue
            if any_prev is None:
                effs.append(cond.name)
                any_prev_new = cond.name
            else:
                eff = self._new_bool(block, cond)
                notp = self._new_bool(block, cond)
                block.append_op(type="logical_not",
                                inputs={"X": [any_prev]},
                                outputs={"Out": [notp.name]})
                block.append_op(type="logical_and",
                                inputs={"X": [cond.name],
                                        "Y": [notp.name]},
                                outputs={"Out": [eff.name]})
                effs.append(eff.name)
                any_prev_new = self._new_bool(block, cond).name
                block.append_op(type="logical_or",
                                inputs={"X": [any_prev],
                                        "Y": [cond.name]},
                                outputs={"Out": [any_prev_new]})
            any_prev = any_prev_new
        # default = NOT (any case cond)
        for i, (cond, _w) in enumerate(self._cases):
            if cond is not None:
                continue
            if any_prev is None:
                effs[i] = None  # no conds at all: default always fires
            else:
                ref = next(c for c, _ in self._cases if c is not None)
                nd = self._new_bool(block, ref)
                block.append_op(type="logical_not",
                                inputs={"X": [any_prev]},
                                outputs={"Out": [nd.name]})
                effs[i] = nd.name
        return effs

    def _merge(self):
        block = self.helper.main_program.current_block()
        targets = []
        for _c, writes in self._cases:
            for n in writes:
                if n not in targets:
                    targets.append(n)
        if not targets:
            return
        effs = self._effective_conds(block)
        for n in targets:
            var = block._find_var_recursive(n)
            enforce(var is not None,
                    "Switch case writes to unknown variable %r" % n)
            # fold in reverse with EFFECTIVE conditions: every case
            # guards every var it writes, and non-writing earlier
            # matches suppress later writes via the eff conds.
            # Base of the chain = the var's prior value; when the var
            # has no readable prior (e.g. created by the startup
            # program only), the default case's write serves unguarded
            # as the base — the only well-defined fallback.
            default_val = None
            for (cond, writes) in self._cases:
                if cond is None and n in writes:
                    default_val = writes[n]
            if self._has_prior(block, n):
                current = n
                guard_default = True
            else:
                enforce(default_val is not None,
                        "Switch writes %r conditionally but the "
                        "variable has no prior value and no default() "
                        "write" % n)
                current = default_val
                guard_default = False
            for (cond, writes), eff in zip(reversed(self._cases),
                                           list(reversed(effs))):
                if n not in writes:
                    continue
                if cond is None and not guard_default:
                    continue  # already the base
                if eff is None:
                    # unconditional default with no case conds at all
                    current = writes[n]
                    continue
                out = block.create_var(
                    name=framework.unique_name.generate(
                        self.helper.name + ".sel"),
                    shape=tuple(var.shape), dtype=var.dtype)
                block.append_op(
                    type="where",
                    inputs={"Condition": [eff], "X": [writes[n]],
                            "Y": [current]},
                    outputs={"Out": [out.name]})
                current = out.name
            # final assign back into the target var name
            block.append_op(type="assign", inputs={"X": [current]},
                            outputs={"Out": [n]})

    def _has_prior(self, block, name):
        """Does ``name`` have a value readable at the merge point —
        fed data, a persistable, or produced by an op outside the
        switch (case writes were redirected to temps)?"""
        var = block._find_var_recursive(name)
        if var is None:
            return False
        if var.persistable or getattr(var, "is_data", False):
            return True
        b = block
        while b is not None:
            for op in b.ops:
                if name in op.output_arg_names:
                    return True
            b = b.parent_block
        return False


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Print a variable's runtime value each step (reference:
    layers/control_flow.py Print -> operators/print_op.cc; the host
    printer is platform/lodtensor_printer.cc). Pass-through: returns a
    var carrying the same value so the print stays in the op graph."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="print", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or input.name,
                            "first_n": first_n, "summarize": summarize,
                            "print_phase": print_phase})
    return out
