"""Control-flow and comparison layers (reference:
python/paddle/fluid/layers/control_flow.py — less_than:1297, equal,
array ops, While:697, IfElse:1553, StaticRNN:406)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["less_than", "less_equal", "greater_than", "greater_equal",
           "equal", "not_equal", "logical_and", "logical_or",
           "logical_xor", "logical_not", "is_empty"]


def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
        cond.stop_gradient = True
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def logical_and(x, y, out=None):
    return _cmp("logical_and", x, y, out)


def logical_or(x, y, out=None):
    return _cmp("logical_or", x, y, out)


def logical_xor(x, y, out=None):
    return _cmp("logical_xor", x, y, out)


def logical_not(x, out=None):
    helper = LayerHelper("logical_not")
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
        out.stop_gradient = True
    helper.append_op(type="logical_not", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond
