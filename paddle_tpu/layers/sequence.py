"""Sequence layers over padded tensors + explicit lengths (reference:
the sequence_* functions in python/paddle/fluid/layers/nn.py and
sequence_ops/ — LoD-based there, padded+lengths here; see
ops/sequence_ops.py for the representation contract)."""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["sequence_pool", "sequence_softmax", "sequence_reverse",
           "sequence_expand", "sequence_expand_as", "sequence_pad",
           "sequence_unpad", "sequence_concat", "sequence_slice",
           "sequence_enumerate", "sequence_first_step",
           "sequence_last_step", "sequence_conv", "sequence_reshape",
           "sequence_scatter", "beam_search", "beam_search_decode"]


def _seq_op(op_type, x, seq_len, attrs=None, name=None,
            extra_inputs=None, out_dtype=None):
    helper = LayerHelper(op_type, name=name)
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    if extra_inputs:
        inputs.update(extra_inputs)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={"Out": [out]}, attrs=attrs or {})
    return out


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  seq_len=None):
    return _seq_op("sequence_pool", input, seq_len,
                   {"pool_type": pool_type, "pad_value": pad_value})


def sequence_softmax(input, use_cudnn=False, name=None, seq_len=None):
    return _seq_op("sequence_softmax", input, seq_len, name=name)


def sequence_reverse(x, name=None, seq_len=None):
    return _seq_op("sequence_reverse", x, seq_len, name=name)


def sequence_first_step(input, seq_len=None):
    return _seq_op("sequence_first_step", input, seq_len)


def sequence_last_step(input, seq_len=None):
    return _seq_op("sequence_last_step", input, seq_len)


def sequence_expand(x, y, ref_level=-1, name=None, y_seq_len=None):
    helper = LayerHelper("sequence_expand", name=name)
    inputs = {"X": [x], "Y": [y]}
    if y_seq_len is not None:
        inputs["SeqLenY"] = [y_seq_len]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_expand", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"ref_level": ref_level})
    return out


def sequence_expand_as(x, y, name=None, y_seq_len=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    inputs = {"X": [x], "Y": [y]}
    if y_seq_len is not None:
        inputs["SeqLenY"] = [y_seq_len]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_expand_as", inputs=inputs,
                     outputs={"Out": [out]})
    return out


def sequence_pad(x, pad_value=0.0, maxlen=None, name=None, seq_len=None):
    """Returns (padded, lengths) like the reference (sequence_pad_op)."""
    helper = LayerHelper("sequence_pad", name=name)
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    out = helper.create_variable_for_type_inference(x.dtype)
    length = helper.create_variable_for_type_inference("int32")
    length.stop_gradient = True
    helper.append_op(type="sequence_pad", inputs=inputs,
                     outputs={"Out": [out], "Length": [length]},
                     attrs={"pad_value": float(pad_value),
                            "padded_length": maxlen if maxlen else -1})
    return out, length


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_concat(input, name=None, seq_lens=None):
    """``input``: list of [B, Ti, ...] vars; ``seq_lens``: matching list
    of length vars (or None). Returns (concatenated, out_lengths)."""
    helper = LayerHelper("sequence_concat", name=name)
    if seq_lens is None:
        seq_lens = []
    out = helper.create_variable_for_type_inference(input[0].dtype)
    out_len = helper.create_variable_for_type_inference("int32")
    out_len.stop_gradient = True
    helper.append_op(type="sequence_concat",
                     inputs={"X": input, "SeqLen": seq_lens},
                     outputs={"Out": [out], "OutLen": [out_len]})
    return out, out_len


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None,
                       seq_len=None):
    return _seq_op("sequence_enumerate", input, seq_len,
                   {"win_size": win_size, "pad_value": pad_value},
                   name=name)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=False, name=None,
                return_parent_idx=True):
    """One dense beam-search step (reference: layers/nn.py beam_search
    -> beam_search_op.cc; fixed-width [batch, beam] redesign — see
    ops/beam_search_ops.py). ``ids`` is accepted for signature parity
    but unused: candidates are the full vocab axis of ``scores``
    ([batch, beam, vocab] log-probs)."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference(pre_ids.dtype)
    sel_scores = helper.create_variable_for_type_inference(
        pre_scores.dtype)
    parent_idx = helper.create_variable_for_type_inference("int32")
    for v in (sel_ids, parent_idx):
        v.stop_gradient = True
    helper.append_op(
        type="beam_search",
        inputs={"PreIds": [pre_ids], "PreScores": [pre_scores],
                "Scores": [scores]},
        outputs={"SelectedIds": [sel_ids],
                 "SelectedScores": [sel_scores],
                 "ParentIdx": [parent_idx]},
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level,
               "is_accumulated": is_accumulated})
    if return_parent_idx:
        return sel_ids, sel_scores, parent_idx
    return sel_ids, sel_scores


def beam_search_decode(ids, parents, scores, beam_size=0, end_id=0,
                       name=None):
    """Backtrack decode-loop tensor arrays into [batch, beam, T]
    sequences sorted best-first (reference: layers/nn.py
    beam_search_decode -> beam_search_decode_op.cc)."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference(ids.dtype)
    sent_scores = helper.create_variable_for_type_inference(
        scores.dtype)
    sent_ids.stop_gradient = True
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Parents": [parents], "Scores": [scores]},
        outputs={"SentenceIds": [sent_ids],
                 "SentenceScores": [sent_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id})
    return sent_ids, sent_scores


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, bias_attr=None, param_attr=None,
                  act=None, name=None, seq_len=None):
    """Context-window convolution over a padded sequence (reference:
    layers/nn.py sequence_conv -> sequence_conv_op.cc)."""
    helper = LayerHelper("sequence_conv", name=name, act=act)
    d = input.shape[-1]
    filt = helper.create_parameter(
        attr=param_attr, shape=(filter_size * d, num_filters),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "Filter": [filt]}
    if seq_len is not None:
        inputs["Lengths"] = [seq_len]
    helper.append_op(type="sequence_conv", inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"context_length": filter_size,
                            "context_stride": filter_stride,
                            "context_start":
                                None if padding else 0})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr,
                                    shape=(num_filters,),
                                    dtype=input.dtype, is_bias=True)
        out = helper.append_bias_op(out, b, axis=2)
    return helper.append_activation(out)


def sequence_reshape(input, new_dim, seq_len=None):
    """Reference: layers/nn.py sequence_reshape ->
    sequence_reshape_op.cc. Returns (out, out_lengths) — the padded
    redesign surfaces the recomputed lengths explicitly."""
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference(
        "int64", stop_gradient=True)
    inputs = {"X": [input]}
    if seq_len is not None:
        inputs["Lengths"] = [seq_len]
    helper.append_op(type="sequence_reshape", inputs=inputs,
                     outputs={"Out": [out], "OutLengths": [out_len]},
                     attrs={"new_dim": new_dim})
    return out, out_len


def sequence_scatter(input, index, updates, name=None, seq_len=None):
    """Reference: layers/nn.py sequence_scatter ->
    sequence_scatter_op.cc."""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "Ids": [index], "Updates": [updates]}
    if seq_len is not None:
        inputs["Lengths"] = [seq_len]
    helper.append_op(type="sequence_scatter", inputs=inputs,
                     outputs={"Out": [out]})
    return out
