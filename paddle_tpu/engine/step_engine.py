"""StepEngine: ONE traced, composable training step.

The runtime used to hold six separately-built step loops — ``run``'s
per-step jit, ``run_repeated``'s fixed-feed scan, ``run_pipelined``'s
chunk scan, the GuardedTrainer retry/rollback driver, the PS trainer
phase, and the sparse runtime's per-step ``wrap_feed``/``push_grads``
loop — each re-assembling the same traced step by hand. This module is
now the only place a step is assembled; everything else routes through
it (docs/step_engine.md has the migration table).

Stages, all orthogonal, all spliced by ``build_step`` into one trace:

  collective transport   GradSyncPlan (exact / rs_ag / q8) at the sync
                         boundary — parallel/collectives.py
  sharded-update bracket ShardedUpdatePlan apply()/finish() around the
                         optimize ops (ZeRO shards + param gather)
  model-axis finisher    finish_model_partials inside the plans on a
                         dp×sp mesh (PR 13) — partial sums pinned
                         replicated before the dp bracket
  anomaly gate           AnomalyGuardPlan pre/post hooks + gated
                         optimize-role writes (PR 2)
  chunking + prefetch    build_chunk_fn's K-step lax.scan over feed xs
                         (PR 4; DevicePrefetcher stages the next chunk)
  host exchange          HostStage hooks at CHUNK boundaries: sparse
                         pull/push (PR 14) with per-step payloads
                         riding the scan xs/ys, and the PS phase
                         (PR 5) at K=1

Composition legality lives in ``engine.rules`` and is shared verbatim
with the static matrix (analysis/matrix.py): a combo the static plane
rejects raises here with the SAME message, so the two planes cannot
drift (the parity gate asserts both directions).

Tracing contract (unchanged from the loops this replaces): step ``i``
at run-counter ``c`` uses ``fold_in(base_key, c+i)`` on the chunked
path — bit-identical to sequential ``run()`` — and persistables ride a
FIXED scan carry (exactly the scope's persistables at trace time).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.enforce import InvalidArgumentError, enforce
from . import rules

__all__ = ["build_step", "build_repeat_fn", "build_chunk_fn",
           "HostStage", "StepEngine"]


def build_step(program, block, fetch_names: Sequence[str],
               library=None, sync_plan=None, guard_plan=None,
               carried=None, warn_dropped: bool = False,
               pipeline_plan=None, mesh=None) -> Callable:
    """Assemble THE traced step: ``step(persist, feed_vals, step_key)
    -> (fetches, persist_out)``.

    ``sync_plan`` / ``guard_plan`` splice at their boundary op indices
    inside ``run_block`` (collective transport, sharded bracket, and
    anomaly gate are all boundary splices — the step stays one XLA
    computation and fusion crosses the seams).

    ``pipeline_plan`` (engine.pipeline.PipelinePlan) splices a third
    stage the same way: it binds against the block HERE (validation is
    assembly-time, not trace-time) and run_block traces the whole
    microbatch schedule at the region start — stage stacking, shifts,
    per-microbatch backward — inside the same one trace the other
    stages splice into. ``mesh`` (optional jax Mesh) lets the bound
    plan route stage shifts over a ``pp`` axis when one is in scope.

    ``carried=None`` (the per-step ``run`` posture) writes back every
    persistable the step produced. A frozenset pins a FIXED carry for
    scan bodies: vars first materialized inside a scan cannot join it;
    ``warn_dropped=True`` additionally warns when such a var appears
    (the pipelined contract — updates outside the carry are discarded
    between chunks)."""
    from .. import framework
    from ..executor import run_block

    bound_pipeline = None
    if pipeline_plan is not None:
        bound_pipeline = pipeline_plan.bind(block, mesh=mesh)

    persistable_names = frozenset(
        n for n, v in block.vars.items() if v.persistable)

    def step(persist, feed_vals, step_key):
        env = dict(persist)
        env.update(feed_vals)
        with framework._trace_program_guard(program):
            run_block(block, env, step_key, library=library,
                      grad_sync=sync_plan, anomaly_guard=guard_plan,
                      pipeline=bound_pipeline)
        if carried is None:
            persist_out = {n: env[n] for n in persistable_names
                           if n in env}
        else:
            if warn_dropped:
                dropped = sorted(n for n in persistable_names
                                 if n in env and n not in carried)
                if dropped:
                    import warnings
                    warnings.warn(
                        "run_pipelined: persistable var(s) %s are "
                        "first materialized inside the scan; their "
                        "updates are DISCARDED between chunks. Run "
                        "the startup program (or one warmup run()) "
                        "first so they join the carry, or use "
                        "chunk_size=1." % (dropped,))
            persist_out = {n: env[n] if n in env else persist[n]
                           for n in carried}
        try:
            fetches = [env[n] for n in fetch_names]
        except KeyError as e:
            raise InvalidArgumentError(
                "fetch var %r is not produced by this program "
                "(known vars: feed %s + program outputs)"
                % (e.args[0], sorted(feed_vals))) from e
        return fetches, persist_out

    return step


def build_repeat_fn(step: Callable, iters: int) -> Callable:
    """K steps of a FIXED feed in one ``lax.scan``:
    ``multi(persist, feed_vals, base_key) -> (last_fetches, persist)``.

    The fetches carry (instead of scan ys stacking) keeps memory O(1)
    in iters; its initial value comes from eval_shape-derived zeros so
    EVERY step runs inside the scan and the step graph is compiled
    exactly once (an inlined step 0 would double the compile of large
    models). PRNG: step ``i`` folds ``i`` into the (already
    counter-folded) base key — run_repeated's documented stream."""

    def multi(persist, feed_vals, base_key):
        fetch_avals, _ = jax.eval_shape(step, persist, feed_vals,
                                        base_key)
        fetches0 = [jnp.zeros(a.shape, a.dtype) for a in fetch_avals]

        def body(carry, i):
            p, _ = carry
            f, p2 = step(p, feed_vals,
                         jax.random.fold_in(base_key, i))
            return (p2, f), None

        (last_persist, last_fetches), _ = jax.lax.scan(
            body, (persist, fetches0), jnp.arange(iters))
        return last_fetches, last_persist

    return multi


def build_chunk_fn(step: Callable,
                   stacked_idx: Sequence[int] = (),
                   pipeline_plan=None) -> Callable:
    """K data-fed steps in one ``lax.scan`` over the chunk xs:
    ``pipelined(persist, chunk, idxs, base_key) ->
    (last_fetches, stacked, persist)``.

    ``pipeline_plan`` is accepted for assembly-API parity with
    ``build_step``: when the step was built WITH a plan, the whole
    microbatch schedule is already inside the step trace, so the chunk
    scan wraps it unchanged — pp × pipelined-chunk composes by
    construction. Passing a plan here only asserts the caller's
    intent matches (a plan object, not truthy garbage).

    ``idxs`` carry ABSOLUTE run counters, so step ``i`` of a chunk
    starting at counter ``c`` uses ``fold_in(base_key, c+i)`` —
    bit-identical to the key the same step would get from a sequential
    ``run()`` call.

    ``stacked_idx`` selects fetch positions whose PER-STEP values ride
    the scan ys stacked ``[K, ...]`` — the chunk-boundary host stages'
    raw material (sparse out-grads for the push). Everything else
    returns last-step-only via the carry, as before."""
    if pipeline_plan is not None:
        from .pipeline import PipelinePlan
        enforce(isinstance(pipeline_plan, PipelinePlan),
                "pipeline_plan must be a PipelinePlan, got %r",
                type(pipeline_plan).__name__)
    stacked_idx = tuple(stacked_idx)

    def pipelined(persist, chunk, idxs, base_key):
        # last-step fetches ride the CARRY (memory O(1) in K) seeded
        # from eval_shape zeros so the step body is traced exactly once
        fetch_avals, _ = jax.eval_shape(
            lambda p, c, i, b: step(
                p, {k: v[0] for k, v in c.items()},
                jax.random.fold_in(b, i[0])),
            persist, chunk, idxs, base_key)
        fetches0 = [jnp.zeros(a.shape, a.dtype) for a in fetch_avals]

        def body(carry, x):
            p, _ = carry
            feed_slice, idx = x
            f, p2 = step(p, feed_slice,
                         jax.random.fold_in(base_key, idx))
            return (p2, f), [f[j] for j in stacked_idx]

        (last_persist, last_fetches), stacked = jax.lax.scan(
            body, (persist, fetches0), (chunk, idxs))
        return last_fetches, stacked, last_persist

    return pipelined


class HostStage:
    """A host-side exchange riding the chunk boundary.

    ``before_chunk`` runs before the dispatch and may rewrite the K
    per-step feeds (the sparse pull stages its embedding payloads here
    — they enter the scan as xs). ``extra_fetch_names`` are fetched
    PER STEP (stacked ``[K, ...]`` via the scan ys) and handed to
    ``after_chunk`` once the dispatch settles. ``kind`` feeds the
    composition rules (engine.rules)."""

    kind = "host"

    def extra_fetch_names(self) -> List[str]:
        return []

    def before_chunk(self, feeds: List[Dict]) -> List[Dict]:
        return feeds

    def after_chunk(self, feeds: List[Dict],
                    stacked: Dict[str, np.ndarray]) -> None:
        pass


class StepEngine:
    """Drives composed chunks through an Executor.

    ``run_chunk`` is the one entry every host-exchanging caller uses:
    the GuardedTrainer step (K=1), the PS trainer phase (K=1 + PS
    stage), and the sparse runtime (K>=1 + sparse stage). Pure
    on-device callers (run_repeated / run_pipelined / run) call the
    builders above directly through the executor — same assembly,
    no host stages."""

    def __init__(self, executor):
        self._exe = executor

    # -- composition legality (shared with the static matrix) ---------
    @staticmethod
    def check_composition(program, k: int = 1,
                          stages: Sequence[HostStage] = ()):
        """Raise InvalidArgumentError with the static matrix's exact
        reason string when the combo is structurally impossible."""
        bs = getattr(program, "_build_strategy", None)
        rej = rules.rejection(
            gradient_sync=getattr(bs, "gradient_sync", None),
            pipelined=k > 1,
            ps=any(st.kind == "ps" for st in stages),
            sparse=any(st.kind == "sparse" for st in stages),
            pp=getattr(bs, "pipeline", None) is not None)
        if rej is not None:
            raise InvalidArgumentError(rej[1])

    # -- the composed chunk -------------------------------------------
    def run_chunk(self, program, feeds: List[Dict], fetch_list=None,
                  scope=None, stages: Sequence[HostStage] = (),
                  return_numpy: bool = True):
        """Run one chunk of ``len(feeds)`` steps with the host stages
        bracketing the single on-device dispatch:

            stage.before_chunk  (sparse pull: K batches, one RPC round)
            one dispatch        (K=1: run(); K>1: run_pipelined scan,
                                 per-step stage fetches stacked as ys)
            stage.after_chunk   (sparse push / PS exchange, in step
                                 order — seqs/acks exactly as the
                                 per-step loop assigned them)

        Returns the LAST step's user fetches (run_pipelined's
        contract)."""
        from ..framework import Variable
        enforce(feeds, "run_chunk needs at least one feed dict")
        feeds = list(feeds)
        k = len(feeds)
        stages = tuple(stages)
        self.check_composition(program, k=k, stages=stages)
        fetch_list = list(fetch_list or [])
        user_names = [f.name if isinstance(f, Variable) else f
                      for f in fetch_list]
        extra: List[str] = []
        for st in stages:
            for n in st.extra_fetch_names():
                if n not in extra:
                    enforce(n not in user_names,
                            "stage fetch %r collides with a user "
                            "fetch", n)
                    extra.append(n)
        for st in stages:
            feeds = st.before_chunk(feeds)

        if k == 1:
            out = self._exe.run(program, feed=feeds[0],
                                fetch_list=fetch_list + extra,
                                scope=scope, return_numpy=False)
            user_out = out[:len(fetch_list)]
            stacked = {n: np.asarray(v)[None] for n, v in
                       zip(extra, out[len(fetch_list):])}
        else:
            names = sorted(feeds[0])
            for f in feeds:
                enforce(sorted(f) == names,
                        "chunk feeds disagree on keys: %s vs %s",
                        sorted(f), names)
            feed_chunk = {n: np.stack([np.asarray(f[n])
                                       for f in feeds]) for n in names}
            user_out, stacked_vals = self._exe.run_pipelined(
                program, feed_chunk, fetch_list=fetch_list,
                stack_fetch_list=extra, scope=scope,
                return_numpy=False)
            stacked = {n: np.asarray(v)
                       for n, v in zip(extra, stacked_vals)}
        for st in stages:
            st.after_chunk(feeds, stacked)
        if return_numpy:
            user_out = [np.asarray(v) for v in user_out]
        return user_out

    def run_step(self, program, feed, fetch_list=None, scope=None,
                 stages: Sequence[HostStage] = (),
                 return_numpy: bool = True):
        """K=1 convenience: one composed step (the GuardedTrainer
        dispatch unit)."""
        return self.run_chunk(program, [feed], fetch_list=fetch_list,
                              scope=scope, stages=stages,
                              return_numpy=return_numpy)
