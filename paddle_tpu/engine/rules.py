"""Composition rules: the ONE place a feature pair is declared
structurally impossible.

Both planes consume this table: the static composition matrix
(analysis/matrix.py) marks the combo ``rejected`` with the reason
string, and the runtime StepEngine refuses to assemble the same combo
by raising ``InvalidArgumentError`` whose message IS the same string.
The parity gate (tests/test_step_engine.py) asserts the two planes
agree cell-for-cell in both directions — a rejection added to one
plane only is a test failure, not a silent drift.

Keys are (feature, feature) pairs; values are the documented reason a
reader (and the matrix report, and the runtime error) gets.
"""

from __future__ import annotations

from typing import Optional, Tuple

REJECTIONS = {
    ("ps", "sharded"): (
        "sharded_update and the PS split both claim the optimize "
        "ops: the bracket runs them on 1/n shards in-graph, the "
        "transpiler moves them server-side. The transpiler already "
        "maps dense parameter serving to ZeRO-sharded state for "
        "pod (non-pserver) runs instead."),
    ("ps", "pipelined"): (
        "the PS grad/param exchange is a host-side per-step phase "
        "(Communicator send/recv around each step); a K-step "
        "on-device chunk scan would silently skip K-1 exchanges."),
}


def rejection(gradient_sync: Optional[str] = None,
              pipelined: bool = False, ps: bool = False,
              sparse: bool = False,
              pp: bool = False) -> Optional[Tuple[tuple, str]]:
    """-> ((feature, feature), reason) when the combo is structurally
    impossible, else None. The sparse exchange deliberately adds no
    rejections: it rides chunk boundaries (K=1 degenerates to the
    per-step flow), so it composes with every other stage — including
    PS at K=1, the reference's Downpour dense+sparse posture.

    ``pp`` (pipeline stages inside the step trace) likewise adds NO
    pairs: the schedule is a region splice inside the one step, so it
    composes with guard, every collective mode, the sharded bracket,
    chunk scans, sparse, and PS alike — per-block structural limits
    (batch_norm, rng ops, skip connections) are bind-time contract
    checks on the specific block, not combo rejections."""
    from ..parallel.collectives import SHARDED_MODES
    if ps and gradient_sync in SHARDED_MODES:
        return ("ps", "sharded"), REJECTIONS[("ps", "sharded")]
    if ps and pipelined:
        return ("ps", "pipelined"), REJECTIONS[("ps", "pipelined")]
    return None
