"""Pipeline stages inside the ONE traced step: gpipe / 1F1B microbatch
scheduling as a first-class engine axis.

A ``PipelinePlan`` partitions a CONTIGUOUS window of a block's forward
ops into ``n_stages`` structurally-identical segments, splits the batch
into ``n_micro`` microbatches, and traces the WHOLE schedule — stacked
per-stage params, stage-shift activation transfers, per-microbatch
backward, gradient accumulation — inside the same step trace that the
guard, collective, sharded-update, and chunk-scan stages splice into
(engine/step_engine.py). The optimizer tail of the block is untouched:
the schedule writes the region's output and every ``@GRAD`` entry the
sequential trace would have produced, so guard × collectives ×
sharded-update × mesh compose with pp exactly as they compose without
it.

Two schedules, one traced tick body:

  gpipe   all M forwards then all M backwards — two ``lax.scan``s of
          ``M + P - 1`` ticks each. Live activations: M microbatch
          inputs per stage (the ring must hold every in-flight
          microbatch until its backward drains).
  1f1b    the steady-state interleave: ONE fused scan of
          ``M + 2P - 1`` ticks whose body runs a forward AND a
          backward tick (each masked by its schedule table), so
          microbatch m's backward at stage s fires at tick
          ``m + 2P - 1 - s`` — the saved-input ring caps at
          ``min(M, 2P - 1)`` microbatches per stage instead of
          gpipe's M, and the measured idle-slot (bubble) fraction
          drops from ``(P-1)/(M+P-1)`` to ``(P-1)/(M+2P-1)``.

The activation shift between adjacent stages is a ``jnp.roll`` of the
stage axis; GSPMD propagates the mesh's ``pp`` sharding through the
scan and lowers the rotation to its own collective. The explicit
formulation — ``lax.ppermute`` under ``shard_map`` plus a
``with_sharding_constraint`` pinning the stage axis to ``pp`` — is kept
behind ``PADDLE_TPU_PP_EXPLICIT_SPMD=1``: on the emulated CPU mesh the
partitioner mis-lowers BOTH (pipelined outputs come back scaled by
exactly dp**2), while the unannotated roll is bit-exact against the
sequential trace. Real TPU backends may opt in to the one-ICI-hop
ppermute form.

Backward is rematerialized: only each stage's INPUT rides the ring;
the stage body is recomputed inside ``jax.vjp`` per microbatch. The
loss tail (the forward ops after the staged region) additionally runs
ONCE at full batch for exact loss/fetch values; its per-microbatch
vjp seeds the pipeline cotangents with ``1/M`` — valid because bind
validates the loss is a scalar batch-mean reduction (``mean`` /
``reduce_mean``), under which the full-batch loss is the mean of the
per-microbatch losses. Equality with the sequential trace therefore
holds up to microbatch reassociation (documented tolerances in
tests/test_step_engine.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.enforce import InvalidArgumentError, enforce

__all__ = ["SCHEDULES", "PipelinePlan", "infer_segments",
           "schedule_tables", "bubble_fraction",
           "peak_live_microbatches", "schedule_forward",
           "stack_stage_params", "validate_microbatches",
           "gpipe_apply_inner"]

SCHEDULES = ("gpipe", "1f1b")

# Op types a staged region/tail may not contain: cross-microbatch batch
# statistics (batch_norm) and host-side sparse rows (lookup_table) both
# break the "microbatches are independent rows" contract the schedule
# is built on; rng ops are rejected separately (the per-op key would
# differ between the full-batch trace and the per-microbatch one).
_REJECT_OP_TYPES = frozenset({"batch_norm", "lookup_table"})


# ---------------------------------------------------------------------
# schedule tables: the static (tick, stage) -> microbatch maps
# ---------------------------------------------------------------------

def _check_sched(schedule, n_micro, n_stages):
    enforce(schedule in SCHEDULES,
            "pipeline schedule must be one of %s, got %r",
            SCHEDULES, schedule)
    enforce(n_stages >= 2, "pipeline needs n_stages >= 2, got %r",
            n_stages)
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1, got %r" % (n_micro,))


def schedule_tables(schedule: str, n_micro: int, n_stages: int):
    """-> (fwd_mb, bwd_mb) int32 arrays [T, P]: the microbatch index
    stage ``s`` works on at tick ``t`` (-1 = idle slot). gpipe's table
    is the fwd-only phase followed by the bwd-only phase; 1f1b fuses
    both into one steady-state table."""
    _check_sched(schedule, n_micro, n_stages)
    M, P = n_micro, n_stages
    t_idx = lambda T: np.arange(T)[:, None]          # noqa: E731
    s_idx = np.arange(P)[None, :]

    def valid(mb):
        return np.where((mb >= 0) & (mb < M), mb, -1).astype(np.int32)

    if schedule == "gpipe":
        Tf = M + P - 1
        fwd_phase = valid(t_idx(Tf) - s_idx)
        bwd_phase = valid(t_idx(Tf) - (P - 1 - s_idx))
        idle = np.full((Tf, P), -1, dtype=np.int32)
        fwd = np.concatenate([fwd_phase, idle])
        bwd = np.concatenate([idle, bwd_phase])
        return fwd, bwd
    T = M + 2 * P - 1
    fwd = valid(t_idx(T) - s_idx)
    bwd = valid(t_idx(T) - (2 * P - 1 - s_idx))
    return fwd, bwd


def bubble_fraction(schedule: str, n_micro: int, n_stages: int) -> float:
    """Fraction of (tick, stage) slots with neither a forward nor a
    backward microbatch — counted from the actual tables, not a closed
    form, so the bench reports what the trace really schedules."""
    fwd, bwd = schedule_tables(schedule, n_micro, n_stages)
    return float(np.mean((fwd < 0) & (bwd < 0)))


def peak_live_microbatches(schedule: str, n_micro: int,
                           n_stages: int) -> int:
    """Saved-activation ring depth per stage: how many microbatch
    inputs are live between their forward and backward. gpipe holds
    all M; 1f1b's steady state caps at ``2P - 1`` (stage s has
    ``2(P-s) - 1`` in flight; the uniform ring takes the max)."""
    _check_sched(schedule, n_micro, n_stages)
    if schedule == "gpipe":
        return n_micro
    return min(n_micro, 2 * n_stages - 1)


def validate_microbatches(batch: int, n_micro: int):
    """The ONE divisibility/arity validation every pipeline entry
    point shares (error strings pinned by tests/test_pipeline.py)."""
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1, got %r" % (n_micro,))
    if batch % n_micro != 0:
        raise ValueError("batch %d not divisible by n_micro %d"
                         % (batch, n_micro))


def stack_stage_params(per_stage_params):
    """[{...}, {...}, ...] (one pytree per stage, equal structure) ->
    one pytree with leading [P] stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


# ---------------------------------------------------------------------
# the stage shift: roll by default, explicit ppermute behind an env gate
# ---------------------------------------------------------------------

def _explicit_pp_spmd() -> bool:
    # The explicit SPMD formulation (shard_map ppermute + a pp
    # sharding constraint on stage-stacked tensors) is opt-in: on the
    # emulated CPU mesh the GSPMD partitioner mis-lowers a partitioned
    # stage-axis rotation inside lax.scan — pipelined outputs come
    # back scaled by exactly dp**2 — while the unannotated jnp.roll
    # formulation partitions correctly (bit-exact vs the sequential
    # trace on a pp=2 x dp=2 mesh). TPU backends can flip this on for
    # the guaranteed single-ICI-hop transfer per tick.
    import os
    return os.environ.get("PADDLE_TPU_PP_EXPLICIT_SPMD", "") == "1"


def _stage_shift(y, direction: int, mesh):
    """Shift the stage axis (axis 0) by one: ``direction=+1`` moves
    stage s's value to stage s+1 (the forward activation hop),
    ``direction=-1`` moves it to stage s-1 (the backward cotangent
    hop). The wrap-around entry is garbage either way and is
    overwritten by the injection slot. Under the opt-in explicit-SPMD
    gate a mesh with a matching ``pp`` axis uses ONE ``lax.ppermute``
    ICI hop per tick instead of the roll."""
    P = y.shape[0]
    if _explicit_pp_spmd() and mesh is not None \
            and "pp" in mesh.axis_names \
            and mesh.shape["pp"] == P and P > 1:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec
        perm = [(i, (i + direction) % P) for i in range(P)]
        return shard_map(
            lambda a: lax.ppermute(a, "pp", perm),
            mesh=mesh, in_specs=PartitionSpec("pp"),
            out_specs=PartitionSpec("pp"), check_rep=False)(y)
    return jnp.roll(y, direction, axis=0)


def _pp_constrain(val, mesh):
    if _explicit_pp_spmd() and mesh is not None \
            and "pp" in mesh.axis_names and mesh.shape["pp"] > 1:
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.lax.with_sharding_constraint(
            val, NamedSharding(mesh, PartitionSpec("pp")))
    return val


# ---------------------------------------------------------------------
# functional scheduler (forward-only): the parallel/pipeline.py shim
# ---------------------------------------------------------------------

def schedule_forward(stage_fn, stacked_params, x_micro, *,
                     schedule: str = "gpipe", mesh=None):
    """Run ``x_micro [M, b, ...]`` through P stages (leading axis of
    ``stacked_params``'s leaves) on the schedule's forward table in ONE
    ``lax.scan``; returns ``y_micro [M, b, ...]``. Differentiable —
    ``jax.grad`` through the scan yields the pipelined backward."""
    leaves = jax.tree_util.tree_leaves(stacked_params)
    enforce(leaves, "stacked_params must have at least one leaf")
    P = leaves[0].shape[0]
    M = x_micro.shape[0]
    fwd_tbl, _ = schedule_tables(schedule, M, max(P, 2))
    if P < 2:  # degenerate single stage: table math still wants P>=2
        fwd_tbl = np.arange(M, dtype=np.int32)[:, None]
    vf = jax.vmap(stage_fn, in_axes=(0, 0))
    y0 = jnp.zeros((P,) + x_micro.shape[1:], x_micro.dtype)
    out0 = jnp.zeros((M + 1,) + x_micro.shape[1:], x_micro.dtype)

    def tick(carry, f_row):
        y_prev, outs = carry
        x_in = _stage_shift(y_prev, 1, mesh).at[0].set(
            x_micro[jnp.clip(f_row[0], 0, M - 1)])
        y = vf(stacked_params, x_in)
        slot = jnp.where(f_row[P - 1] >= 0, f_row[P - 1], M)
        outs = outs.at[slot].set(y[P - 1])
        return (y, outs), None

    # drop all-idle ticks (gpipe's table carries the bwd-only phase)
    rows = [r for r in np.asarray(fwd_tbl) if (r >= 0).any()]
    (_, outs), _ = lax.scan(tick, (y0, out0),
                            jnp.asarray(np.stack(rows)))
    return outs[:M]


def gpipe_apply_inner(stage_fn, stage_params, x_micro, *, axis_name,
                      n_stages):
    """Per-shard GPipe body (call inside shard_map) — the engine-owned
    implementation behind ``parallel.pipeline.gpipe_apply_inner``.

    stage_fn(params, x) -> y — one stage's computation; the SAME
    callable runs on every stage with that stage's params shard. Input
    and output must have identical shape/dtype (the activation that
    travels the pipe). ``x_micro [M, ...]``: every stage receives the
    same array, only stage 0 reads it. Returns ``y_micro [M, ...]``:
    real on the LAST stage, zeros elsewhere."""
    stage = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    P = n_stages
    fwd_perm = [(i, (i + 1) % P) for i in range(P)]

    carry_act = jnp.zeros_like(x_micro[0])
    out_buf = jnp.zeros_like(x_micro)

    def tick(carry, t):
        act, outs = carry
        mb = lax.dynamic_index_in_dim(x_micro, jnp.minimum(t, M - 1),
                                      keepdims=False)
        inp = jnp.where(stage == 0, mb, act)
        y = stage_fn(stage_params, inp)
        done_idx = t - (P - 1)
        outs = lax.cond(
            jnp.logical_and(stage == P - 1, done_idx >= 0),
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(done_idx, 0), 0),
            lambda o: o, outs)
        act_next = lax.ppermute(y, axis_name, fwd_perm)
        return (act_next, outs), None

    (_, out_buf), _ = lax.scan(tick, (carry_act, out_buf),
                               jnp.arange(M + P - 1))
    return out_buf


# ---------------------------------------------------------------------
# PipelinePlan: the engine-axis contract
# ---------------------------------------------------------------------

class PipelinePlan:
    """Stage partition of a block's forward op window + microbatch
    count + schedule. Rides ``BuildStrategy.pipeline`` into
    ``build_step`` (and keys the executor's jit cache via
    ``signature()``). ``segments=None`` infers the stage windows from
    the block's op-type structure at bind time."""

    def __init__(self, n_stages: int, n_micro: int,
                 schedule: str = "1f1b",
                 segments: Optional[Sequence[Sequence[int]]] = None):
        _check_sched(schedule, n_micro, n_stages)
        if segments is not None:
            segments = tuple(tuple(int(i) for i in seg)
                             for seg in segments)
            enforce(len(segments) == n_stages,
                    "segments has %d entries for n_stages=%d",
                    len(segments), n_stages)
        self.n_stages = int(n_stages)
        self.n_micro = int(n_micro)
        self.schedule = schedule
        self.segments = segments

    def signature(self):
        return ("pp", self.n_stages, self.n_micro, self.schedule,
                self.segments)

    @property
    def bubble_fraction(self) -> float:
        return bubble_fraction(self.schedule, self.n_micro,
                               self.n_stages)

    @property
    def peak_live_microbatches(self) -> int:
        return peak_live_microbatches(self.schedule, self.n_micro,
                                      self.n_stages)

    def __repr__(self):
        return ("PipelinePlan(n_stages=%d, n_micro=%d, schedule=%r)"
                % (self.n_stages, self.n_micro, self.schedule))

    def bind(self, block, mesh=None):
        """Validate the plan against a block and return the
        ``_BoundPipeline`` run_block splices at the region start.
        Raises InvalidArgumentError when the block's structure cannot
        be staged (the reason names the violated contract)."""
        if mesh is not None and "pp" in mesh.axis_names:
            enforce(mesh.shape["pp"] == self.n_stages,
                    "mesh 'pp' axis has %d devices but the plan has "
                    "%d stages — one stage per pp shard",
                    mesh.shape["pp"], self.n_stages)
        segments = self.segments
        if segments is None:
            segments = infer_segments(block, self.n_stages)
        return _BoundPipeline(self, block, segments, mesh)


def _forward_len(block) -> int:
    for i, op in enumerate(block.ops):
        if op.type in ("vjp", "vjp2") \
                or op.attrs.get("op_role") in ("backward", "optimize"):
            return i
    return len(block.ops)


def _op_sig(op):
    """Structural signature of one op: type + attrs (sans roles).
    Segments must match op-for-op on this."""
    attrs = {k: repr(v) for k, v in op.attrs.items()
             if k not in ("op_role", "op_namescope")}
    return (op.type, tuple(sorted(attrs.items())))


def infer_segments(block, n_stages: int) -> List[List[int]]:
    """Find P contiguous, structurally-identical, equal-length op
    windows ending before the loss tail. Tries the LONGEST segments
    first and the LATEST start first (minimal tail), validating each
    candidate with a full bind; raises when no partition binds."""
    P = n_stages
    fwd_len = _forward_len(block)
    last_err = None
    for L in range(fwd_len // P, 0, -1):
        for start in range(fwd_len - P * L, -1, -1):
            sig0 = [_op_sig(block.ops[start + j]) for j in range(L)]
            if any(_op_sig(block.ops[start + s * L + j]) != sig0[j]
                   for s in range(1, P) for j in range(L)):
                continue
            segs = [list(range(start + s * L, start + (s + 1) * L))
                    for s in range(P)]
            try:
                _BoundPipeline(
                    PipelinePlan(P, 1, "gpipe", segments=segs),
                    block, tuple(tuple(s) for s in segs), None)
                return segs
            except InvalidArgumentError as e:
                last_err = e
                continue
    raise InvalidArgumentError(
        "no %d-stage partition of the forward ops binds: the block "
        "needs %d contiguous structurally-identical op windows before "
        "the loss tail%s"
        % (P, P, " (last candidate failed: %s)" % last_err
           if last_err is not None else ""))


class _BoundPipeline:
    """A plan validated against one block: segment name maps, external
    classification, the run_block skip set, and ``execute`` (the traced
    schedule + env injection)."""

    def __init__(self, plan: PipelinePlan, block, segments, mesh):
        self.plan = plan
        self.block = block
        self.mesh = mesh
        P = plan.n_stages
        ops_l = block.ops
        fwd_len = _forward_len(block)

        segs = [list(seg) for seg in segments]
        enforce(len(segs) == P, "plan has %d segments for n_stages=%d",
                len(segs), P)
        L = len(segs[0])
        enforce(L >= 1 and all(len(s) == L for s in segs),
                "pipeline segments must be equal-length and non-empty")
        flat = [i for s in segs for i in s]
        enforce(flat == list(range(segs[0][0], segs[0][0] + P * L)),
                "pipeline segments must be contiguous op windows")
        self.region_start = segs[0][0]
        self.region_end = segs[0][0] + P * L
        enforce(self.region_end <= fwd_len,
                "pipeline segments reach op %d but the forward pass "
                "ends at op %d", self.region_end, fwd_len)
        self.fwd_len = fwd_len

        for i in range(self.region_start, fwd_len):
            self._check_stageable_op(ops_l[i], i)

        # grad suffix of the (single) backward pass over this block
        suffixes = {op.attrs.get("grad_suffix", "")
                    for op in ops_l if op.type in ("vjp", "vjp2")}
        enforce(len(suffixes) <= 1,
                "pipeline cannot stage a block with multiple backward "
                "passes (grad suffixes %s)", sorted(suffixes))
        self.grad_suffix = next(iter(suffixes), "")
        self.has_backward = bool(suffixes)

        for i, op in enumerate(ops_l):
            if op.type == "vjp2" and \
                    op.attrs.get("fwd_op_index", -1) >= self.region_start:
                raise InvalidArgumentError(
                    "double backward (vjp2, op #%d) through the "
                    "pipelined region is not supported" % i)

        self._map_segments(block, segs)
        self._classify_tail(block)

        skip = set(range(self.region_start, self.region_end))
        for i, op in enumerate(ops_l):
            if op.type in ("vjp", "vjp2") and \
                    self.region_start <= op.attrs.get("fwd_op_index",
                                                      -1) < fwd_len:
                skip.add(i)
        self.skip = frozenset(skip)

    # -- bind-time validation helpers ---------------------------------

    def _check_stageable_op(self, op, i):
        from .. import ops as ops_mod
        from ..ops.control_flow_ops import ARRAY_OP_TYPES
        if op.type in ("vjp", "vjp2") or not ops_mod.has(op.type):
            raise InvalidArgumentError(
                "pipeline region/tail op #%d (%r) has no plain "
                "lowering to stage" % (i, op.type))
        if op.type in _REJECT_OP_TYPES or op.type in ARRAY_OP_TYPES:
            raise InvalidArgumentError(
                "op type %r (op #%d) cannot be pipelined: it couples "
                "rows across the batch or requires eager execution"
                % (op.type, i))
        if ops_mod.get(op.type).needs_rng \
                and not op.attrs.get("is_test"):
            # is_test=True makes the rng key inert (dropout rate is
            # forced to 0 in the lowering), so inference-mode ops are
            # replay-safe even though the registry marks them rng
            raise InvalidArgumentError(
                "op type %r (op #%d) needs per-op rng: the "
                "per-microbatch replay would draw different keys than "
                "the sequential trace" % (op.type, i))

    def _map_segments(self, block, segs):
        """Build sigma[s]: segment-0 name -> segment-s name, classify
        region externals (stacked per-stage params vs shared consts),
        and pin the single homogeneous boundary variable."""
        P = self.plan.n_stages
        ops_l = block.ops
        seg_sets = [set(s) for s in segs]

        def producer(name):
            for i in range(self.fwd_len - 1, -1, -1):
                if name in ops_l[i].output_arg_names:
                    return i
            return None

        # positional name isomorphism: walking the segments op-by-op,
        # every (input+output) name of segment s must map 1:1 from the
        # name at the same position in segment 0
        sigmas: List[Dict[str, str]] = [{} for _ in range(P)]
        produced0 = set()
        ext_order: List[str] = []
        for j in range(len(segs[0])):
            o0 = ops_l[segs[0][j]]
            for s in range(P):
                os_ = ops_l[segs[s][j]]
                in0, ins = o0.input_arg_names, os_.input_arg_names
                out0, outs = o0.output_arg_names, os_.output_arg_names
                enforce(len(in0) == len(ins) and len(out0) == len(outs),
                        "segment op arity mismatch at op #%d vs #%d",
                        segs[0][j], segs[s][j])
                for n0, ns in zip(list(in0) + list(out0),
                                  list(ins) + list(outs)):
                    if n0 in sigmas[s]:
                        enforce(sigmas[s][n0] == ns,
                                "segment %d is not isomorphic to "
                                "segment 0: %r maps to both %r and %r",
                                s, n0, sigmas[s][n0], ns)
                    else:
                        sigmas[s][n0] = ns
            for n0 in o0.input_arg_names:
                if n0 not in produced0 and n0 not in ext_order:
                    ext_order.append(n0)
            produced0.update(o0.output_arg_names)

        # the boundary: for s >= 1 exactly ONE external image is
        # produced by the previous segment; its aligned position is
        # the stage input (identical across segments, or the stage
        # function cannot be one template)
        in_pos = None
        for s in range(1, P):
            bpos = [k for k, n0 in enumerate(ext_order)
                    if producer(sigmas[s][n0]) in seg_sets[s - 1]]
            enforce(len(bpos) == 1,
                    "exactly one activation must cross the stage %d->"
                    "%d boundary, found %d", s - 1, s, len(bpos))
            enforce(in_pos is None or in_pos == bpos[0],
                    "stage boundary variable position drifts across "
                    "segments")
            in_pos = bpos[0]
        self.in_name = ext_order[in_pos]
        p_in = producer(self.in_name)
        enforce(p_in is None or p_in < self.region_start,
                "segment 0's input %r must come from before the "
                "region", self.in_name)

        # segment 0's boundary-out (TEMPLATE name) is segment 1's
        # image of the stage input; the region output is the last
        # segment's image of that template name
        self.out_template = sigmas[1][self.in_name]
        enforce(self.out_template in produced0,
                "internal error: template boundary-out %r not "
                "produced by segment 0", self.out_template)
        self.out_name = sigmas[P - 1][self.out_template]

        # no cross-stage skip connections: a var produced in stage s
        # and consumed after that segment must be exactly the boundary
        # activation, consumed exactly by stage s+1 (or, for the last
        # stage, by the tail)
        for s in range(P):
            bvar = sigmas[s][self.out_template]
            for i in range(segs[s][-1] + 1, self.fwd_len):
                for n in ops_l[i].input_arg_names:
                    if producer(n) not in seg_sets[s]:
                        continue
                    if s + 1 < P:
                        enforce(n == bvar and i in seg_sets[s + 1],
                                "var %r produced in stage %d is "
                                "consumed at op #%d — only the "
                                "boundary activation may leave a "
                                "stage", n, s, i)
                    else:
                        enforce(n == bvar and i >= self.region_end,
                                "var %r produced in the last stage is "
                                "consumed at op #%d — only the region "
                                "output may feed the tail", n, i)

        # boundary homogeneity: the activation that travels the pipe
        # keeps one shape/dtype through every stage
        v_in = block.vars[self.in_name]
        v_out = block.vars[self.out_name]
        enforce(tuple(v_in.shape) == tuple(v_out.shape)
                and v_in.dtype == v_out.dtype,
                "stage input %r %s/%s and output %r %s/%s must have "
                "identical shape and dtype (the activation that "
                "travels the pipe)", self.in_name, v_in.shape,
                v_in.dtype, self.out_name, v_out.shape, v_out.dtype)

        # externals (minus the boundary): shared vs stacked
        self.stacked_names: List[str] = []
        self.shared_names: List[str] = []
        gname = lambda n: n + "@GRAD" + self.grad_suffix  # noqa: E731
        for k, n0 in enumerate(ext_order):
            if k == in_pos:
                continue
            names = [sigmas[s][n0] for s in range(P)]
            for n in names:
                p = producer(n)
                enforce(p is None or p < self.region_start,
                        "stage external %r is produced inside the "
                        "region (op #%s) — cross-stage skip "
                        "connections cannot be pipelined", n, p)
            if all(n == n0 for n in names):
                if self.has_backward and block.has_var(gname(n0)):
                    raise InvalidArgumentError(
                        "external %r is shared by every stage AND "
                        "receives gradients — the schedule cannot "
                        "accumulate a shared-stage grad; give each "
                        "stage its own parameter" % n0)
                self.shared_names.append(n0)
                continue
            shapes = {tuple(block.vars[n].shape) for n in names}
            dtypes = {block.vars[n].dtype for n in names}
            enforce(len(shapes) == 1 and len(dtypes) == 1,
                    "per-stage external %r cannot stack: shapes %s / "
                    "dtypes %s differ across stages", n0,
                    sorted(shapes), sorted(dtypes))
            enforce(all(block.vars[n].persistable for n in names),
                    "per-stage external %r must be persistable "
                    "parameters to stack across stages", n0)
            self.stacked_names.append(n0)

        self.sigmas = sigmas
        self.template = [(i, ops_l[i]) for i in segs[0]]
        self.segs = segs

    def _classify_tail(self, block):
        """Tail = forward ops after the region (the loss head). Runs
        full-batch in the normal trace for exact fetch values AND
        per-microbatch inside the schedule to seed cotangents."""
        ops_l = block.ops
        self.tail = [(i, ops_l[i])
                     for i in range(self.region_end, self.fwd_len)]
        gname = lambda n: n + "@GRAD" + self.grad_suffix  # noqa: E731

        produced = {self.out_name}
        self.tail_param_names: List[str] = []
        self.tail_batch_names: List[str] = []
        self.tail_shared_names: List[str] = []
        for i, op in self.tail:
            for n in op.input_arg_names:
                if n in produced or n in self.tail_param_names \
                        or n in self.tail_batch_names \
                        or n in self.tail_shared_names:
                    continue
                var = block.vars.get(n)
                enforce(var is not None,
                        "tail op #%d consumes unknown var %r", i, n)
                if var.persistable:
                    self.tail_param_names.append(n)
                elif var.is_data:
                    self.tail_batch_names.append(n)
                else:
                    prod = None
                    for j in range(self.region_start, self.region_end):
                        if n in ops_l[j].output_arg_names:
                            prod = j
                            break
                    if prod is not None:
                        raise InvalidArgumentError(
                            "tail op #%d consumes %r produced inside "
                            "the pipelined region (op #%d) — only the "
                            "final stage activation may feed the loss "
                            "tail" % (i, n, prod))
                    if self.has_backward and block.has_var(gname(n)):
                        raise InvalidArgumentError(
                            "tail input %r needs gradients but is "
                            "neither the stage output nor a "
                            "persistable parameter — a skip "
                            "connection around the pipeline region "
                            "cannot be staged" % n)
                    self.tail_shared_names.append(n)
            produced.update(op.output_arg_names)

        if not self.has_backward:
            self.loss_name = None
            return
        enforce(self.tail,
                "a pipelined training block needs a loss tail after "
                "the staged region (the backward seed op must follow "
                "at least one tail op)")
        loss_i, loss_op = self.tail[-1]
        # derive the loss var from the backward seed when present
        loss_name = loss_op.output_arg_names[0]
        suffix = "@GRAD" + self.grad_suffix
        if self.fwd_len < len(ops_l):
            seed = ops_l[self.fwd_len]
            if seed.type == "fill_constant" and seed.output_arg_names:
                cand = seed.output_arg_names[0]
                if cand.endswith(suffix):
                    loss_name = cand[:-len(suffix)]
        prod = None
        for i, op in self.tail:
            if loss_name in op.output_arg_names:
                prod = op
        enforce(prod is not None,
                "loss var %r is not produced by the pipeline tail",
                loss_name)
        enforce(prod.type in ("mean", "reduce_mean"),
                "the pipelined loss must be a batch-mean reduction "
                "(mean/reduce_mean) so per-microbatch losses combine "
                "as loss = (1/M) * sum(loss_m); got %r", prod.type)
        lv = block.vars[loss_name]
        numel = 1
        for d in lv.shape:
            numel *= max(int(d), 1)
        enforce(numel == 1,
                "the pipelined loss %r must be a scalar, got shape %s",
                loss_name, lv.shape)
        self.loss_name = loss_name

    # -- the traced schedule ------------------------------------------

    def execute(self, env: Dict, step_key, library=None):
        """Trace the full microbatch schedule into ``env``: writes the
        region output, the region-input grad, every per-stage param
        grad, and every tail param grad — exactly the entries the
        skipped sequential ops would have produced."""
        from ..executor import run_op

        plan, mesh = self.plan, self.mesh
        P, M = plan.n_stages, plan.n_micro
        x_full = env[self.in_name]
        B = int(x_full.shape[0])
        if B % M != 0:
            raise InvalidArgumentError(
                "pipeline: batch %d not divisible by n_micro %d"
                % (B, M))
        b = B // M
        feat = tuple(x_full.shape[1:])
        # feeds arrive as host numpy — promote before tracer indexing
        x_micro = jnp.asarray(x_full).reshape((M, b) + feat)

        stacked = [
            _pp_constrain(jnp.stack([env[self.sigmas[s][n0]]
                                     for s in range(P)]), mesh)
            for n0 in self.stacked_names]
        shared_vals = {n: env[n] for n in self.shared_names}

        def stage_fn(leaves, x):
            local = dict(shared_vals)
            local.update(zip(self.stacked_names, leaves))
            local[self.in_name] = x
            for gi, op in self.template:
                run_op(op, local, step_key, gi, library=library)
            return local[self.out_template]

        vf = jax.vmap(stage_fn, in_axes=(0, 0))
        fwd_tbl, bwd_tbl = schedule_tables(plan.schedule, M, P)
        S = peak_live_microbatches(plan.schedule, M, P)
        zP = jnp.zeros((P, b) + feat, x_full.dtype)
        saved0 = jnp.zeros((P, S + 1, b) + feat, x_full.dtype)
        buf0 = jnp.zeros((M + 1, b) + feat, x_full.dtype)
        arangeP = jnp.arange(P)

        def fwd_tick(carry, f_row):
            y_prev, saved, out_buf = carry
            x_in = _stage_shift(y_prev, 1, mesh).at[0].set(
                x_micro[jnp.clip(f_row[0], 0, M - 1)])
            y = _pp_constrain(vf(stacked, x_in), mesh)
            slots = jnp.where(f_row >= 0, f_row % S, S)
            saved = saved.at[arangeP, slots].set(x_in)
            ob = jnp.where(f_row[P - 1] >= 0, f_row[P - 1], M)
            out_buf = out_buf.at[ob].set(y[P - 1])
            return (y, saved, out_buf), None

        if not self.has_backward:
            (_, _, out_buf), _ = lax.scan(
                fwd_tick, (zP, saved0, buf0),
                jnp.asarray(fwd_tbl[np.any(fwd_tbl >= 0, axis=1)]))
            env[self.out_name] = out_buf[:M].reshape((B,) + feat)
            return

        tail_params = [env[n] for n in self.tail_param_names]
        tail_shared = {n: env[n] for n in self.tail_shared_names}
        bexts_micro = []
        for n in self.tail_batch_names:
            v = env[n]
            if int(v.shape[0]) != B:
                raise InvalidArgumentError(
                    "pipeline tail data var %r has leading dim %d; "
                    "expected the batch %d" % (n, v.shape[0], B))
            bexts_micro.append(
                jnp.asarray(v).reshape((M, b) + tuple(v.shape[1:])))

        def tail_fn(tparams, x, bexts):
            local = dict(tail_shared)
            local.update(zip(self.tail_param_names, tparams))
            local.update(zip(self.tail_batch_names, bexts))
            local[self.out_name] = x
            for gi, op in self.tail:
                run_op(op, local, step_key, gi, library=library)
            return local[self.loss_name]

        def stage_bwd(leaves, x, g):
            _, pull = jax.vjp(stage_fn, leaves, x)
            dl, dx = pull(g)
            return dx, dl

        vb = jax.vmap(stage_bwd, in_axes=(0, 0, 0))
        gacc0 = [jnp.zeros_like(a) for a in stacked]
        tg0 = [jnp.zeros_like(v) for v in tail_params]

        def bwd_half(saved, out_buf, dx_prev, gacc, tgacc, dxout,
                     b_row):
            """One backward tick (shared by the gpipe bwd phase and
            the fused 1f1b body). Reads the ring/out_buf BEFORE the
            caller's forward writes of the same tick."""
            bslots = jnp.where(b_row >= 0, b_row % S, S)
            x_saved = saved[arangeP, bslots]
            bl = b_row[P - 1]
            x_t = out_buf[jnp.clip(bl, 0, M - 1)]
            bx = [bm[jnp.clip(bl, 0, M - 1)] for bm in bexts_micro]
            loss_mb, pull = jax.vjp(
                lambda tp, xx: tail_fn(tp, xx, bx), tail_params, x_t)
            dtp, gseed = pull(jnp.full_like(loss_mb, 1.0 / M))
            live_t = bl >= 0
            tgacc = [a + jnp.where(live_t, d, jnp.zeros_like(d))
                     for a, d in zip(tgacc, dtp)]
            g_in = _stage_shift(dx_prev, -1, mesh).at[P - 1].set(gseed)
            dx, dl = vb(stacked, x_saved, g_in)
            live = b_row >= 0
            gacc = [a + jnp.where(
                live.reshape((P,) + (1,) * (d.ndim - 1)), d,
                jnp.zeros_like(d)) for a, d in zip(gacc, dl)]
            sl0 = jnp.where(b_row[0] >= 0, b_row[0], M)
            dxout = dxout.at[sl0].set(dx[0])
            return dx, gacc, tgacc, dxout

        if plan.schedule == "gpipe":
            fwd_rows = jnp.asarray(
                fwd_tbl[np.any(fwd_tbl >= 0, axis=1)])
            bwd_rows = jnp.asarray(
                bwd_tbl[np.any(bwd_tbl >= 0, axis=1)])
            (_, saved, out_buf), _ = lax.scan(
                fwd_tick, (zP, saved0, buf0), fwd_rows)

            def bwd_tick(carry, b_row):
                dx_prev, gacc, tgacc, dxout = carry
                return bwd_half(saved, out_buf, dx_prev, gacc, tgacc,
                                dxout, b_row), None

            (_, gacc, tgacc, dxout), _ = lax.scan(
                bwd_tick, (zP, gacc0, tg0, buf0), bwd_rows)
        else:
            def fused_tick(carry, rows):
                y_prev, dx_prev, saved, out_buf, gacc, tgacc, dxout \
                    = carry
                f_row, b_row = rows
                # backward FIRST: at S = 2P-1 the stage-0 ring slot a
                # backward reads is rewritten by the SAME tick's
                # forward
                dx, gacc, tgacc, dxout = bwd_half(
                    saved, out_buf, dx_prev, gacc, tgacc, dxout,
                    b_row)
                (y, saved, out_buf), _ = fwd_tick(
                    (y_prev, saved, out_buf), f_row)
                return (y, dx, saved, out_buf, gacc, tgacc,
                        dxout), None

            (_, _, _, out_buf, gacc, tgacc, dxout), _ = lax.scan(
                fused_tick, (zP, zP, saved0, buf0, gacc0, tg0, buf0),
                (jnp.asarray(fwd_tbl), jnp.asarray(bwd_tbl)))

        env[self.out_name] = out_buf[:M].reshape((B,) + feat)
        gname = lambda n: n + "@GRAD" + self.grad_suffix  # noqa: E731
        if self.block.has_var(gname(self.in_name)):
            env[gname(self.in_name)] = \
                dxout[:M].reshape((B,) + feat)
        for n0, g in zip(self.stacked_names, gacc):
            for s in range(P):
                ns = self.sigmas[s][n0]
                if self.block.has_var(gname(ns)):
                    env[gname(ns)] = g[s]
        for n, g in zip(self.tail_param_names, tgacc):
            if self.block.has_var(gname(n)):
                env[gname(n)] = g
