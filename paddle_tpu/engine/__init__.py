"""The step engine: one traced, composable training step.

``build_step`` assembles the single traced step every runtime path
dispatches (guard × collectives × sharded bracket × mesh finisher);
``build_repeat_fn`` / ``build_chunk_fn`` wrap it in the K-step scans;
``StepEngine`` drives composed chunks with host-exchange stages (PS,
sparse) riding the chunk boundaries. ``PipelinePlan`` makes pipeline
(pp) stages a build_step axis: the whole gpipe/1F1B microbatch
schedule traces inside the same one step. ``rules`` is the shared
composition-legality table — the static matrix and the runtime engine
reject the same combos with the same message.
"""

from . import rules  # noqa: F401
from .pipeline import (PipelinePlan, infer_segments,  # noqa: F401
                       stack_stage_params)
from .step_engine import (HostStage, StepEngine,  # noqa: F401
                          build_chunk_fn, build_repeat_fn, build_step)

__all__ = ["rules", "HostStage", "StepEngine", "build_step",
           "build_repeat_fn", "build_chunk_fn", "PipelinePlan",
           "infer_segments", "stack_stage_params"]
