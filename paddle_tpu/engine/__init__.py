"""The step engine: one traced, composable training step.

``build_step`` assembles the single traced step every runtime path
dispatches (guard × collectives × sharded bracket × mesh finisher);
``build_repeat_fn`` / ``build_chunk_fn`` wrap it in the K-step scans;
``StepEngine`` drives composed chunks with host-exchange stages (PS,
sparse) riding the chunk boundaries. ``rules`` is the shared
composition-legality table — the static matrix and the runtime engine
reject the same combos with the same message.
"""

from . import rules  # noqa: F401
from .step_engine import (HostStage, StepEngine,  # noqa: F401
                          build_chunk_fn, build_repeat_fn, build_step)

__all__ = ["rules", "HostStage", "StepEngine", "build_step",
           "build_repeat_fn", "build_chunk_fn"]
