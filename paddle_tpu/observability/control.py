"""Closed-loop control plane: verdict-driven remediation + router
autoscaling with a machine-auditable action ledger.

PR 7 built the SIGNALS (metrics/journal/traces), PR 10 the VERDICTS
(watchdog stalls, declarative HealthRules, doctor's offline ranking).
This module closes the loop — at fleet scale there is no human reading
a blackbox, so verdicts must DRIVE remediation, and (critically for an
observability plane) every automated action must itself be observable:

  - **RemediationPolicy** — a declarative binding from a trigger to an
    actuator. Triggers are ``"verdict:<reason-prefix>"`` (watchdog
    problems, e.g. ``verdict:stall:serving_batcher`` for a wedged
    batcher) or ``"event:<journal-kind>"`` (e.g.
    ``event:replica_evicted`` for a SIGKILLed replica). Actuators are
    plain callables registered next to the policy — the supervisor
    actions live WITH the component that owns them (a replica
    respawner in the serving harness, ``ListenAndServ.quarantine`` for
    a flaky pserver) while this module owns WHEN they may run.
  - **ScalingPolicy** — router-driven autoscaling: spawn/retire
    serving replicas from SUSTAINED queue-depth pressure, with
    hysteresis (the up threshold sits above the down threshold and the
    sustain clock resets inside the band, so oscillation around a
    threshold never flaps the fleet), min/max replica bounds, and the
    rolling-EWMA pressure baseline journalled with every decision.
    The actuator is a ``scaler`` duck (``tools/load_gen.FleetScaler``
    over ``spawn_fleet``): spawned replicas inherit the fleet's shared
    compile-cache dir, so scale-up warms from the PR 11 persistent
    cache and never cold-compiles in the request path.
  - **Safety rails** — per-policy cooldowns, a GLOBAL action-rate
    limiter (a flapping sensor must not become an action storm), and
    the scaling bounds/hysteresis above. Suppressed decisions are
    ledgered exactly like fired ones.
  - **The action ledger** — every decision emits one
    ``control_action`` journal event carrying the policy, action,
    decision (``fired``/``failed``/``suppressed``), the triggering
    verdict/event, ``role@seq`` evidence citations, suppress reason,
    and cooldown state. Policies announce themselves with
    ``control_policy_armed`` (trigger + deadline), so
    ``tools/doctor.py --expect``'s ``remediation_audit`` pass can
    prove — from the journal alone — that every action had a cause and
    every armed verdict was remediated inside its deadline.
  - **Probation** — a quarantine-style action may return a ``probe``
    callable: the control plane probes each tick and fires the
    ``readmit`` callable after ``ok_needed`` consecutive successes
    (evict + probation + readmit-on-probe), ledgered as its own
    ``control_action`` citing the original quarantine.

``GET /healthz`` grows a ``control`` block (armed policies, recent
actions, suppression counts) via ``health.register_control_provider``.

Locking: decisions are computed under ``self._mu`` but every journal
emit happens AFTER the lock is dropped (the ``ps.py _event_locked``
discipline ``tools/lock_lint.py`` enforces) — actuators run outside
the lock too, since they may call back into arbitrary runtime locks.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from . import journal as _journal
from . import health as _health
from .registry import registry as _registry

__all__ = ["RemediationPolicy", "ScalingPolicy", "ControlPlane"]


def _cite(e: Optional[dict], **extra) -> dict:
    """One ``role@seq`` evidence citation for the ledger (the same
    shape doctor's detectors emit, so audit chains are greppable)."""
    out = {"role": None, "seq": None, "kind": None}
    if e:
        out = {"role": e.get("role"), "seq": e.get("seq"),
               "kind": e.get("kind")}
        for f in ("reason", "replica", "endpoint", "detail"):
            if f in e:
                out[f] = e[f]
    out.update(extra)
    return out


class RemediationPolicy:
    """One declarative verdict->action binding.

    - ``trigger``: ``"verdict:<reason-prefix>"`` matches active
      watchdog problems by reason prefix;``"event:<kind>"`` matches
      new journal events by exact kind.
    - ``action``: the actuator name the ledger records (the callable
      itself is registered alongside via
      ``ControlPlane.register_policy``).
    - ``cooldown_s``: minimum spacing between fires of THIS policy
      (re-triggers inside it are ledgered as suppressed).
    - ``deadline_s``: the audit contract — a matching verdict with no
      fired action within this window is an un-remediated verdict and
      fails ``doctor --expect``.
    """

    def __init__(self, name: str, trigger: str, action: str,
                 cooldown_s: float = 30.0, deadline_s: float = 60.0):
        if not (trigger.startswith("verdict:")
                or trigger.startswith("event:")):
            raise ValueError(
                "trigger must be 'verdict:<reason-prefix>' or "
                "'event:<journal-kind>', got %r" % (trigger,))
        self.name = name
        self.trigger = trigger
        self.action = action
        self.cooldown_s = float(cooldown_s)
        self.deadline_s = float(deadline_s)

    @property
    def kind(self) -> str:
        return "verdict" if self.trigger.startswith("verdict:") \
            else "event"

    @property
    def selector(self) -> str:
        return self.trigger.split(":", 1)[1]

    def describe(self) -> dict:
        return {"policy": self.name, "trigger": self.trigger,
                "action": self.action, "cooldown_s": self.cooldown_s,
                "deadline_s": self.deadline_s}


class ScalingPolicy:
    """Router-driven autoscaling rails.

    Pressure is the router's queue depth per healthy replica
    (``ServingRouter.pressure()``). ``up_depth`` must exceed
    ``down_depth`` — the gap IS the hysteresis band: inside it the
    sustain clocks reset, so pressure oscillating around either
    threshold can never flap the fleet. A scale decision additionally
    requires the condition to hold for ``sustain_s`` continuously,
    respects ``min_replicas``/``max_replicas`` (out-of-bounds wants
    are ledgered as suppressed), and shares the global action-rate
    limiter with every other policy."""

    def __init__(self, name: str = "router_autoscale",
                 up_depth: float = 8.0, down_depth: float = 1.0,
                 sustain_s: float = 3.0, cooldown_s: float = 15.0,
                 min_replicas: int = 1, max_replicas: int = 4,
                 deadline_s: float = 120.0, target: str = "serving",
                 p99_factor: Optional[float] = None,
                 p99_floor_ms: float = 0.0):
        if not up_depth > down_depth:
            raise ValueError(
                "up_depth (%.3g) must exceed down_depth (%.3g) — the "
                "gap is the hysteresis band" % (up_depth, down_depth))
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if target not in ("serving", "trainer", "pserver"):
            raise ValueError(
                "target must be 'serving', 'trainer' or 'pserver', "
                "got %r" % (target,))
        if p99_factor is not None and not float(p99_factor) > 1.0:
            raise ValueError(
                "p99_factor must exceed 1.0 (it multiplies the p99 "
                "EWMA baseline), got %r" % (p99_factor,))
        self.name = name
        self.up_depth = float(up_depth)
        self.down_depth = float(down_depth)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.deadline_s = float(deadline_s)
        # which stateful/stateless plane this policy actuates — purely
        # declarative (the scaler duck does the plane-specific work)
        # but ledgered with every decision so the audit can tell a
        # trainer grow from a serving spawn
        self.target = target
        # p99-vs-EWMA: a FIRST-CLASS scale-up trigger next to queue
        # depth. Fires when the live p99 exceeds ``p99_factor`` x its
        # own EWMA baseline (and ``p99_floor_ms``, so microsecond
        # noise on an idle fleet can't trip the ratio), sustained like
        # the depth trigger. The baseline FREEZES while the trigger is
        # hot — folding the regression into its own baseline would
        # normalize it away mid-sustain.
        self.p99_factor = None if p99_factor is None \
            else float(p99_factor)
        self.p99_floor_ms = float(p99_floor_ms)

    def describe(self) -> dict:
        out = {"policy": self.name, "trigger": "pressure",
               "action": "scale", "cooldown_s": self.cooldown_s,
               "deadline_s": self.deadline_s,
               "up_depth": self.up_depth,
               "down_depth": self.down_depth,
               "sustain_s": self.sustain_s,
               "min_replicas": self.min_replicas,
               "max_replicas": self.max_replicas,
               "target": self.target}
        if self.p99_factor is not None:
            out["p99_factor"] = self.p99_factor
            out["p99_floor_ms"] = self.p99_floor_ms
        return out


class _ScalerState:
    __slots__ = ("policy", "scaler", "above_since", "below_since",
                 "ewma", "p99_ewma")

    def __init__(self, policy, scaler):
        self.policy = policy
        self.scaler = scaler
        self.above_since: Optional[float] = None
        self.below_since: Optional[float] = None
        self.ewma: Optional[float] = None
        self.p99_ewma: Optional[float] = None


class ControlPlane:
    """The supervisor: subscribes to watchdog verdicts, journal events
    and router pressure, and executes declarative policies through
    registered actuators — every decision (including suppressed ones)
    lands in the action ledger. ``start()`` runs the evaluation as a
    daemon thread at ``interval_s``; tests drive ``tick()`` directly.

    ``max_actions_per_min`` is the GLOBAL rate limiter across every
    policy: a flapping sensor (or a mis-tuned rule) can at worst cost
    that many actions per minute, never an action storm.

    Actuators run SYNCHRONOUSLY on the evaluation thread — a
    deliberate tradeoff: the ledger stays strictly ordered (one
    decision fully executes and records before the next) at the cost
    that one slow actuator delays the other policies' evaluation by
    its runtime. Keep actuators bounded (the shipped ones are: an
    in-process respawn is seconds, a subprocess spawn is bounded by
    its startup timeout) and size ``deadline_s`` to cover the slowest
    actuator that can run ahead of a policy's own."""

    def __init__(self, watchdog=None, interval_s: float = 0.5,
                 max_actions_per_min: int = 6,
                 ledger_capacity: int = 256,
                 policy_file: Optional[str] = None):
        self._wd = watchdog
        self.interval_s = float(interval_s)
        self.max_actions_per_min = int(max_actions_per_min)
        # declarative persistence: policies registered through a NAMED
        # actuator are mirrored to this JSON file, and start() re-arms
        # any spec whose actuator name is registered — so a supervisor
        # restart (new ControlPlane, same policy_file) resumes the
        # exact policy set it was running, not a blank slate
        self.policy_file = policy_file
        self._mu = threading.Lock()
        self._policies: List = []         # (policy, actuator)
        self._scalers: List[_ScalerState] = []
        self._actuators: Dict[str, object] = {}
        self._specs: List[dict] = []      # persistable policy specs
        # trigger bookkeeping, all RECENCY-BOUNDED (the supervisor is
        # the one process designed never to restart — no set may grow
        # with uptime): keys are seq-monotonic, so oldest-first
        # eviction is safe
        self._handled = collections.OrderedDict()   # fired/failed
        self._suppress_noted = collections.OrderedDict()
        # event-trigger instances held back by a rail: the journal
        # window has already moved past them, so they are retried
        # from here each tick until they fire — a second replica
        # dying inside the first one's cooldown must be remediated
        # when the cooldown opens, not silently dropped
        self._deferred = collections.OrderedDict()
        # per-(policy, reason) high-water of handled verdict raises:
        # when the raise event ages out of the bounded journal ring
        # while the problem is still active, this (not the ring)
        # proves the episode was already acted on — no duplicate
        # remediation of an already-replaced component
        self._last_raise_handled: Dict = {}
        self._cooldowns: Dict[str, float] = {}
        self._action_times: "collections.deque" = collections.deque()
        self._probations: List[dict] = []
        self._ledger: "collections.deque" = collections.deque(
            maxlen=int(ledger_capacity))
        self._counts = {"fired": 0, "failed": 0, "suppressed": 0}
        # event triggers act on journal events AFTER this plane came
        # up — history must never re-trigger remediation
        self._last_seq = self._watermark()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._armed = False
        self._was_stopped = False
        self._m_actions = {
            d: _registry().counter("control_actions_total", decision=d)
            for d in ("fired", "failed", "suppressed")}

    # -- arming -------------------------------------------------------
    def register_actuator(self, name: str, actuator):
        """Register a NAMED actuator (a remediation callable or a
        scaler duck). Names are the persistence seam: a policy armed
        through a name can be written to ``policy_file`` and re-armed
        by a future supervisor that registers the same name — the
        callable itself can't survive a restart, the binding can."""
        with self._mu:
            self._actuators[str(name)] = actuator
        return self

    def _resolve(self, ref):
        if not isinstance(ref, str):
            return ref, None
        with self._mu:
            act = self._actuators.get(ref)
        if act is None:
            raise KeyError(
                "no actuator registered under %r — call "
                "register_actuator(name, fn) first" % (ref,))
        return act, ref

    def _persist_spec(self, spec: dict):
        """Mirror one persistable policy spec to the policy file
        (atomic rewrite; only name-bound policies are persistable)."""
        with self._mu:
            self._specs = [s for s in self._specs
                           if s["spec"].get("name") != spec["spec"]
                           .get("name")] + [spec]
            specs = list(self._specs)
        if not self.policy_file:
            return
        tmp = self.policy_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"policies": specs}, f, indent=1,
                      sort_keys=True)
        os.replace(tmp, self.policy_file)

    def _rearm_from_file(self):
        """Re-arm persisted policy specs whose actuator names are
        registered (start()-time; specs for unknown actuators stay in
        the file untouched — they re-arm when their owner shows up)."""
        if not self.policy_file or not os.path.exists(self.policy_file):
            return
        try:
            with open(self.policy_file) as f:
                specs = (json.load(f) or {}).get("policies", [])
        except Exception as e:
            _journal.emit("control_plane_error", action="raise",
                          error="policy_file unreadable: %r" % (e,))
            return
        with self._mu:
            armed = {p.name for p, _ in self._policies} \
                | {s.policy.name for s in self._scalers}
            actuators = dict(self._actuators)
        for entry in specs:
            spec, act_name = entry.get("spec", {}), entry.get(
                "actuator")
            if spec.get("name") in armed or act_name not in actuators:
                continue
            if entry.get("type") == "scaling":
                self.attach_scaler(act_name, ScalingPolicy(**spec))
            else:
                self.register_policy(RemediationPolicy(**spec),
                                     act_name)

    def register_policy(self, policy: RemediationPolicy,
                        actuator):
        """Arm one remediation policy. ``actuator(ctx)`` runs OUTSIDE
        the control-plane lock with ``ctx`` = {"policy", "reason",
        "problem"?, "event"?}; its return value is ledgered (a dict
        with a ``probe``/``readmit`` pair additionally enters
        probation — see class docstring). ``actuator`` may be a
        registered actuator NAME, which also makes the policy
        persistable to ``policy_file``."""
        act, act_name = self._resolve(actuator)
        with self._mu:
            self._policies.append((policy, act))
        if act_name is not None:
            self._persist_spec({
                "type": "remediation", "actuator": act_name,
                "spec": {"name": policy.name,
                         "trigger": policy.trigger,
                         "action": policy.action,
                         "cooldown_s": policy.cooldown_s,
                         "deadline_s": policy.deadline_s}})
        _journal.emit("control_policy_armed", **policy.describe())
        return policy

    def attach_scaler(self, scaler,
                      policy: Optional[ScalingPolicy] = None):
        """Arm autoscaling over a ``scaler`` duck: ``replica_count()``,
        ``pressure()`` (or a router with one), ``scale_up()``,
        ``scale_down()`` — ``tools/load_gen.FleetScaler`` is the
        subprocess-fleet implementation; trainer/pserver elasticity
        ducks (tools/chaos_run.py) actuate the stateful planes through
        the same surface. ``scaler`` may be a registered actuator
        NAME, which also makes the policy persistable."""
        policy = policy or ScalingPolicy()
        duck, act_name = self._resolve(scaler)
        with self._mu:
            self._scalers.append(_ScalerState(policy, duck))
        if act_name is not None:
            spec = {"name": policy.name, "up_depth": policy.up_depth,
                    "down_depth": policy.down_depth,
                    "sustain_s": policy.sustain_s,
                    "cooldown_s": policy.cooldown_s,
                    "min_replicas": policy.min_replicas,
                    "max_replicas": policy.max_replicas,
                    "deadline_s": policy.deadline_s,
                    "target": policy.target}
            if policy.p99_factor is not None:
                spec["p99_factor"] = policy.p99_factor
                spec["p99_floor_ms"] = policy.p99_floor_ms
            self._persist_spec({"type": "scaling",
                                "actuator": act_name, "spec": spec})
        _journal.emit("control_policy_armed", **policy.describe())
        return policy

    def start(self):
        """Arm the /healthz control block and start the daemon.
        Re-startable: a stopped plane re-registers its provider."""
        if not self._armed:
            self._armed = True
            # keep the exact bound-method object so stop() can tell
            # OUR registration from another plane's
            self._provider = self.control_block
            _health.register_control_provider(self._provider)
        # persisted specs re-arm FIRST (so a restarted supervisor's
        # re-announcements below cover them too)
        self._rearm_from_file()
        if self._was_stopped:
            # events from the stopped window are history, not
            # triggers: whatever happened while the plane was down was
            # handled by whoever ran the fleet then — the same
            # "history never re-triggers" contract as construction
            self._last_seq = self._watermark()
            self._was_stopped = False
            # re-announce every armed policy: the audit window after a
            # restart must see its own control_policy_armed records,
            # not depend on pre-restart history surviving the ring
            with self._mu:
                described = [p.describe() for p, _ in self._policies] \
                    + [s.policy.describe() for s in self._scalers]
            for d in described:
                _journal.emit("control_policy_armed", rearmed=True,
                              **d)
        if self._thread is None or not self._thread.is_alive():
            self._stop = threading.Event()
            # the loop gets ITS OWN stop event: stop()'s bounded join
            # can expire while an actuator blocks a tick (a spawn can
            # legitimately take ~2 min), and a zombie loop re-reading
            # the rebound self._stop would never see its set flag —
            # two concurrent planes racing every policy
            self._thread = threading.Thread(
                target=self._loop, args=(self._stop,),
                daemon=True, name="control-plane")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        self._armed = False
        self._was_stopped = True
        # only clear the /healthz provider if it is still OURS — a
        # second plane's registration must survive this one's stop
        if getattr(_health, "_CONTROL_PROVIDER", None) is \
                getattr(self, "_provider", None):
            _health.register_control_provider(None)

    def _loop(self, stop):
        last_err, repeats = None, 0
        while not stop.wait(self.interval_s):
            try:
                self.tick()
                if last_err is not None:
                    _journal.emit("control_plane_error",
                                  action="clear", repeats=repeats)
                last_err, repeats = None, 0
            except Exception as e:
                # the control plane must never take the process down —
                # but a plane that dies every tick must not die
                # SILENTLY while /healthz still shows it armed: journal
                # the error once per distinct failure (a permanent bug
                # is one loud event, not a 2/s storm)
                err = repr(e)
                repeats += 1
                if err != last_err:
                    _journal.emit("control_plane_error",
                                  action="raise", error=err)
                    last_err = err

    @staticmethod
    def _watermark() -> int:
        evs = _journal.events()
        return evs[-1]["seq"] if evs else 0

    def _watchdog(self):
        if self._wd is None:
            self._wd = _health.get_watchdog()
        return self._wd

    # -- evaluation ---------------------------------------------------
    def tick(self) -> List[dict]:
        """One full evaluation: verdict + event triggers, scaling,
        probations. Returns the ledger records it produced (each also
        emitted as a ``control_action`` journal event)."""
        now = time.monotonic()
        records: List[dict] = []
        try:
            problems = (self._watchdog().verdict() or {}).get(
                "problems", [])
        except Exception:
            problems = []
        new_events = _journal.events(since_seq=self._last_seq)
        if new_events:
            self._last_seq = new_events[-1]["seq"]
        with self._mu:
            policies = list(self._policies)
            scalers = list(self._scalers)
            deferred = list(self._deferred.items())
        # the newest raise event per reason — the citation that makes
        # an action's cause checkable against the raw record. Only
        # verdict policies consume it: don't scan the whole journal
        # ring several times a second on an event/scaling-only plane
        raises: Dict[str, dict] = {}
        if any(pol.kind == "verdict" for pol, _ in policies):
            for e in _journal.events(kind="health"):
                if e.get("action") == "raise":
                    raises[e.get("reason")] = e
        try:
            # rail-held event instances first: their journal events are
            # behind the window now, so this queue is their only way
            # back
            for key, (pol, act, reason, evidence, ctx) in deferred:
                rec = self._decide(pol, act, key, reason, evidence,
                                   ctx, now)
                if rec is not None:
                    records.append(rec)
                with self._mu:
                    if key in self._handled:
                        self._deferred.pop(key, None)
            for pol, act in policies:
                for key, reason, evidence, ctx in self._instances(
                        pol, problems, raises, new_events):
                    rec = self._decide(pol, act, key, reason, evidence,
                                       ctx, now)
                    if rec is not None:
                        records.append(rec)
            for st in scalers:
                records.extend(self._tick_scaler(st, now))
            records.extend(self._tick_probations(now))
        finally:
            # ledger emits strictly AFTER all decision locks dropped —
            # and even when a later phase raised: an action that RAN
            # must reach the ledger, else it is the unexplained actor
            # this module exists to forbid
            for rec in records:
                ev = _journal.emit("control_action", **rec)
                if ev is not None:
                    rec["seq"] = ev["seq"]
                self._m_actions[rec["decision"]].inc()
            with self._mu:
                self._ledger.extend(records)
                for rec in records:
                    self._counts[rec["decision"]] += 1
        return records

    def _instances(self, pol, problems, raises, new_events):
        """Trigger instances for one policy this tick:
        [(dedup_key, reason, evidence, ctx)]."""
        out = []
        if pol.kind == "verdict":
            for p in problems:
                reason = str(p.get("reason", ""))
                if not reason.startswith(pol.selector):
                    continue
                ev = raises.get(reason)
                seq = ev["seq"] if ev else None
                last = self._last_raise_handled.get(
                    (pol.name, reason))
                if last is not None and (seq is None
                                         or seq <= last):
                    # same episode: either this exact raise was
                    # already acted on, or the raise aged out of the
                    # journal ring while the problem stayed active —
                    # never re-remediate an already-handled verdict
                    continue
                key = (pol.name, reason, seq)
                out.append((key, reason,
                            [_cite(ev, reason=reason)],
                            {"policy": pol.name, "reason": reason,
                             "problem": dict(p), "event": ev}))
        else:
            for e in new_events:
                if e.get("kind") != pol.selector:
                    continue
                key = (pol.name, e["kind"], e["seq"])
                out.append((key, e["kind"], [_cite(e)],
                            {"policy": pol.name, "reason": e["kind"],
                             "event": dict(e)}))
        return out

    @staticmethod
    def _bounded_add(od, key, cap=4096):
        """Insert into a recency-bounded OrderedDict; True when the
        key was already present."""
        if key in od:
            return True
        od[key] = True
        while len(od) > cap:
            od.popitem(last=False)
        return False

    def _rate_open_locked(self, now) -> bool:
        while self._action_times and \
                now - self._action_times[0] > 60.0:
            self._action_times.popleft()
        return len(self._action_times) < self.max_actions_per_min

    def _decide(self, pol, act, key, reason, evidence, ctx, now):
        """Safety rails for one trigger instance -> ledger record (or
        None when this instance was already handled/noted)."""
        with self._mu:
            if key in self._handled:
                return None
            fired_at = self._cooldowns.get(pol.name)
            cooling = fired_at is not None and \
                now - fired_at < pol.cooldown_s
            remaining = round(pol.cooldown_s - (now - fired_at), 3) \
                if cooling else 0.0
            rate_open = self._rate_open_locked(now)
            if cooling or not rate_open:
                why = "cooldown" if cooling else "rate_limit"
                if pol.kind == "event":
                    # the journal window has moved past this event:
                    # park the instance for retry once the rail opens
                    if key not in self._deferred:
                        self._deferred[key] = (pol, act, reason,
                                               evidence, ctx)
                        while len(self._deferred) > 4096:
                            self._deferred.popitem(last=False)
                already = self._bounded_add(self._suppress_noted,
                                            (key, why))
                if already:
                    return None
            else:
                prev_last = self._last_raise_handled.get(
                    (pol.name, reason))
                self._bounded_add(self._handled, key)
                if pol.kind == "verdict":
                    self._last_raise_handled[(pol.name, reason)] = \
                        key[2] if key[2] is not None else -1
                self._cooldowns[pol.name] = now
                self._action_times.append(now)
        if cooling or not rate_open:
            return self._record(
                pol.name, pol.action, "suppressed", reason, evidence,
                suppress_reason="cooldown" if cooling else "rate_limit",
                cooldown_remaining_s=remaining)
        rec = self._run_action(pol.name, pol.action, act, reason,
                               evidence, ctx)
        if rec["decision"] == "failed":
            # a FAILED remediation must stay remediable: un-handle the
            # instance so it retries once the (already-consumed)
            # cooldown reopens — bounded by the same rails as any
            # action, each attempt ledgered. A permanently-failing
            # actuator then shows up as failed records AND, past the
            # policy deadline, as an un-remediated verdict in the
            # audit — the correct signal, not silent abandonment.
            with self._mu:
                self._handled.pop(key, None)
                if pol.kind == "verdict":
                    if prev_last is None:
                        self._last_raise_handled.pop(
                            (pol.name, reason), None)
                    else:
                        self._last_raise_handled[(pol.name, reason)] \
                            = prev_last
                elif key not in self._deferred:
                    self._deferred[key] = (pol, act, reason,
                                           evidence, ctx)
        return rec

    def _run_action(self, policy, action, act, reason, evidence, ctx,
                    **extra):
        t0 = time.monotonic()
        try:
            result = act(ctx)
            decision = "fired"
        except Exception as e:
            result, decision = {"error": repr(e)}, "failed"
        took = round(time.monotonic() - t0, 4)
        prob_err = None
        if isinstance(result, dict) and callable(result.get("probe")):
            # one probation per (policy, action, target): a re-fire for
            # the same component RESTARTS its probation (fresh evidence,
            # fresh clock) instead of appending a duplicate — the list
            # is bounded by the registered policy set, not uptime.
            # Actuators guarding several components under one policy
            # disambiguate via result["target"].
            try:
                entry = {
                    "key": (policy, action, result.get("target")),
                    "policy": policy, "action": action,
                    "reason": reason,
                    "probe": result["probe"],
                    "readmit": result.get("readmit"),
                    "ok_needed": int(result.get("ok_needed", 3)),
                    "deadline_s": float(
                        result.get("probe_deadline_s", 600.0)),
                    "started": t0,
                    "oks": 0, "evidence": list(evidence)}
            except Exception as e:
                # the actuator already RAN — a malformed probation
                # shape must not raise the record away (an executed but
                # unledgered action is the exact thing this module
                # forbids); ledger the action with the defect noted
                prob_err = repr(e)
            else:
                with self._mu:
                    self._probations = [
                        p for p in self._probations
                        if p["key"] != entry["key"]]
                    self._probations.append(entry)
        summary = result if isinstance(result, dict) else (
            None if result is None else repr(result))
        if isinstance(summary, dict):
            summary = {k: v for k, v in summary.items()
                       if not callable(v)}
        return self._record(policy, action, decision, reason,
                            evidence, result=summary,
                            action_seconds=took,
                            probation_error=prob_err, **extra)

    @staticmethod
    def _record(policy, action, decision, reason, evidence, **extra):
        rec = {"policy": policy, "action": action,
               "decision": decision, "reason": reason,
               "evidence": list(evidence)}
        rec.update({k: v for k, v in extra.items() if v is not None})
        return rec

    # -- scaling ------------------------------------------------------
    def _clear_scaler_notes_locked(self, pol):
        for d in ("up", "down"):
            for w in ("bounds", "cooldown", "rate_limit"):
                self._suppress_noted.pop((pol.name, d, w), None)

    def _pressure(self, st) -> Optional[dict]:
        scaler = st.scaler
        try:
            if hasattr(scaler, "pressure"):
                p = scaler.pressure()
            else:
                p = scaler.router.pressure()
        except Exception:
            return None
        return p if isinstance(p, dict) else {"depth_per_replica":
                                              float(p)}

    def _tick_scaler(self, st, now) -> List[dict]:
        pol = st.policy
        p = self._pressure(st)
        if p is None:
            return []
        depth = float(p.get("depth_per_replica") or 0.0)
        # rolling EWMA baseline: journalled with every decision so a
        # reader can see what "normal" looked like when the plane acted
        st.ewma = depth if st.ewma is None \
            else 0.8 * st.ewma + 0.2 * depth
        # p99-vs-EWMA trigger: a latency regression is pressure even
        # when the queue looks shallow (stragglers, a degraded member
        # slowing its group's executor). Baseline freezes while hot —
        # see ScalingPolicy.__init__.
        p99 = p.get("p99_ms")
        p99_hot = False
        if pol.p99_factor is not None and p99 is not None:
            p99 = float(p99)
            base = st.p99_ewma
            p99_hot = (base is not None and p99 >= pol.p99_floor_ms
                       and p99 >= pol.p99_factor * base)
            if not p99_hot:
                st.p99_ewma = p99 if base is None \
                    else 0.8 * base + 0.2 * p99
        if depth >= pol.up_depth or p99_hot:
            st.above_since = st.above_since or now
            st.below_since = None
            want = "up" if now - st.above_since >= pol.sustain_s \
                else None
        elif depth <= pol.down_depth and p.get("healthy", 1) != 0:
            # healthy == 0 is a total outage, not idleness: the
            # pressure fallback reads a drained pending count as "no
            # load", and retiring recovery capacity mid-outage is the
            # one move that can never be right — hold instead
            st.below_since = st.below_since or now
            st.above_since = None
            want = "down" if now - st.below_since >= pol.sustain_s \
                else None
        else:
            # the hysteresis band: both sustain clocks reset, and any
            # suppression episode from the last excursion closes
            st.above_since = st.below_since = None
            with self._mu:
                self._clear_scaler_notes_locked(pol)
            return []
        if want is None:
            return []
        try:
            n = int(st.scaler.replica_count())
        except Exception:
            return []
        if want == "up":
            reason = "router_pressure_high" if depth >= pol.up_depth \
                else "router_p99_regression"
        else:
            reason = "router_pressure_low"
        out_of_bounds = (want == "up" and n >= pol.max_replicas) or \
                        (want == "down" and n <= pol.min_replicas)
        if want == "down" and not out_of_bounds:
            # an actuator that owns only part of the fleet (FleetScaler
            # never retires the base replicas) exposes how many it can
            # actually take back; "nothing retirable" is a bounds
            # condition, NOT a failed action — firing anyway would burn
            # the cooldown + a rate-limiter slot on a guaranteed
            # failure, forever, on any idle fleet above min_replicas
            rc = getattr(st.scaler, "retirable_count", None)
            if callable(rc):
                try:
                    out_of_bounds = int(rc()) <= 0
                except Exception:
                    pass
        with self._mu:
            fired_at = self._cooldowns.get(pol.name)
            cooling = fired_at is not None and \
                now - fired_at < pol.cooldown_s
            rate_open = self._rate_open_locked(now)
            if out_of_bounds or cooling or not rate_open:
                why = "bounds" if out_of_bounds else (
                    "cooldown" if cooling else "rate_limit")
                if self._bounded_add(self._suppress_noted,
                                     (pol.name, want, why)):
                    return []
                suppressed = why
            else:
                suppressed = None
                self._cooldowns[pol.name] = now
                self._action_times.append(now)
                st.above_since = st.below_since = None
                self._clear_scaler_notes_locked(pol)
        detail = dict(p, ewma_baseline=round(st.ewma, 4),
                      threshold=pol.up_depth if want == "up"
                      else pol.down_depth, replicas=n,
                      target=pol.target)
        if st.p99_ewma is not None:
            detail["p99_ewma_baseline"] = round(st.p99_ewma, 4)
        if suppressed is not None:
            return [self._record(
                pol.name, "scale_%s" % want, "suppressed", reason,
                [_cite(None, reason=reason, pressure=detail)],
                suppress_reason=suppressed)]
        # the pressure signal is its own journal event, emitted BEFORE
        # the action so the ledger's cause precedes its effect in seq
        # order (and the audit has a verdict to chain to)
        sig = _journal.emit("control_signal", reason=reason,
                            policy=pol.name, **detail)
        act = st.scaler.scale_up if want == "up" \
            else st.scaler.scale_down
        rec = self._run_action(
            pol.name, "scale_%s" % want,
            lambda _ctx: act(), reason,
            [_cite(sig, reason=reason)], {"pressure": detail},
            pressure=detail)
        return [rec]

    # -- probation ----------------------------------------------------
    def _tick_probations(self, now) -> List[dict]:
        with self._mu:
            probs = list(self._probations)
        out = []
        done = []
        for pr in probs:
            if now - pr["started"] > pr["deadline_s"]:
                # a component that never passes its probe must not pin
                # a probation (and its per-tick probe cost) forever:
                # give up loudly — the failed record IS the signal that
                # the quarantined component needs a human after all
                done.append(pr)
                out.append(self._record(
                    pr["policy"], "readmit:%s" % pr["action"],
                    "failed", "probation_expired",
                    list(pr["evidence"]),
                    result={"error": "probe never passed within "
                                     "%.0fs deadline" % pr["deadline_s"]},
                    probes_ok=pr["oks"]))
                continue
            try:
                ok = bool(pr["probe"]())
            except Exception:
                ok = False
            pr["oks"] = pr["oks"] + 1 if ok else 0
            if pr["oks"] < pr["ok_needed"]:
                continue
            done.append(pr)
            decision = "fired"
            result = None
            if callable(pr.get("readmit")):
                try:
                    result = pr["readmit"]()
                except Exception as e:
                    result, decision = {"error": repr(e)}, "failed"
            out.append(self._record(
                pr["policy"], "readmit:%s" % pr["action"], decision,
                "probation_passed", list(pr["evidence"]),
                result=result if isinstance(result, dict)
                else (None if result is None else repr(result)),
                probes_ok=pr["ok_needed"]))
        if done:
            with self._mu:
                self._probations = [p for p in self._probations
                                    if p not in done]
        return out

    # -- introspection ------------------------------------------------
    def ledger(self) -> List[dict]:
        with self._mu:
            return list(self._ledger)

    def control_block(self) -> dict:
        """The ``/healthz`` ``control`` block: what is armed, what
        recently happened, what was held back."""
        with self._mu:
            armed = [p.describe() for p, _ in self._policies] \
                + [s.policy.describe() for s in self._scalers]
            recent = [
                {k: r.get(k) for k in ("policy", "action", "decision",
                                       "reason", "suppress_reason",
                                       "seq")}
                for r in list(self._ledger)[-8:]]
            counts = dict(self._counts)
            probations = [{"policy": p["policy"],
                           "action": p["action"], "oks": p["oks"],
                           "ok_needed": p["ok_needed"]}
                          for p in self._probations]
            in_window = len(self._action_times)
        return {"armed_policies": armed, "recent_actions": recent,
                "counts": counts, "probations": probations,
                "rate_limiter": {"max_per_min": self.max_actions_per_min,
                                 "in_window": in_window}}

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
