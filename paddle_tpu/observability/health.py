"""Per-role health plane: watchdog, flight recorder, and the
machine-readable ``/healthz`` verdict.

PR 7 built the SIGNALS (MetricsRegistry, event journal, traces); this
module is what WATCHES them. Three pieces:

  - **Beacon + Watchdog** — a beacon is a cheap monotonic progress
    counter bumped by a hot loop (executor dispatch completion,
    serving batcher iteration, pserver barrier release, router
    request completion, prefetcher chunks). The watchdog daemon
    thread checks every armed watch each tick: a beacon that shows NO
    progress for ``deadline_s`` while its ``pending_fn`` reports work
    outstanding is a **stall** verdict — the "silent 240 s backend
    hang" class the bench history (BENCH_r03→r05) made expensive.
    Declarative ``HealthRule``s over MetricsRegistry deltas catch the
    softer failures: recompile storms, throughput collapse vs a
    rolling baseline, queue saturation, anomaly-skip burn rate.
    Verdicts flow out three ways: a ``health`` journal event on every
    raise/clear, a ``health_state{role,reason}`` gauge, and the
    upgraded ``GET /healthz`` (export.py) that returns this module's
    ``healthz()`` payload instead of an unconditional 200.

  - **FlightRecorder** — the black box: a bounded ring of metric
    samples plus all-thread stack captures
    (``sys._current_frames``), dumped as ``blackbox.<role>.json``
    (stacks + journal tail + metrics + beacon ages) on SIGTERM, fatal
    error, or a watchdog stall verdict, so a SIGKILLed replica or a
    wedged dispatch leaves evidence a human (or ``tools/doctor.py``)
    can read after the fact. ``faulthandler`` is chained onto SIGTERM
    too, so even a process whose main thread is parked inside a C
    call (the observed ``jax.devices()`` hang) writes its stacks.

  - **healthz() / provider plumbing** — the process singleton
    watchdog backs ``GET /healthz``; 200 while healthy/degraded
    (degraded is advisory), 503 on an unhealthy verdict, body always
    the full JSON verdict.

``tools/doctor.py`` is the offline half: it turns journals + these
blackbox dumps into a ranked, evidence-cited root-cause verdict.

Cost posture: a beacon bump is one lock + int add per *dispatch/loop
iteration* (not per step); the watchdog is one daemon thread at
``interval_s`` (default 0.5 s) that reads counters. The
``health_overhead`` bench row (bench.py --all) keeps this < 2% on the
pipelined CPU probe.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from . import journal as _journal
from .registry import MetricsRegistry, registry

__all__ = ["Beacon", "beacon", "beacons_snapshot", "HealthRule",
           "Watchdog", "FlightRecorder", "get_watchdog",
           "get_recorder", "set_blackbox_dir", "arm_process",
           "default_rules", "healthz", "register_control_provider"]

ENV_BLACKBOX_DIR = "PADDLE_TPU_BLACKBOX_DIR"

SEVERITY_RANK = {"healthy": 0, "degraded": 1, "unhealthy": 2}


# ---------------------------------------------------------------------------
# beacons
# ---------------------------------------------------------------------------

class Beacon:
    """A monotonic progress counter with a last-bump timestamp — the
    watchdog's cheapest input. Hot loops hold the object and ``bump()``
    once per unit of progress (one dispatch, one batch, one barrier
    release); cost is one lock + one int add."""

    __slots__ = ("name", "_mu", "_count", "_t_last")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self._count = 0
        self._t_last = time.monotonic()

    def bump(self, n: int = 1):
        with self._mu:
            self._count += n
            self._t_last = time.monotonic()

    def read(self):
        """-> (count, monotonic time of last bump)."""
        with self._mu:
            return self._count, self._t_last

    @property
    def count(self) -> int:
        with self._mu:
            return self._count


_BEACONS: Dict[str, Beacon] = {}
_BEACONS_MU = threading.Lock()


def beacon(name: str) -> Beacon:
    """Process-wide named beacon (memoized). Components that need a
    private progress counter (one per Executor instance, say)
    construct ``Beacon`` directly and hand it to ``Watchdog.watch``."""
    b = _BEACONS.get(name)
    if b is not None:
        return b
    with _BEACONS_MU:
        b = _BEACONS.get(name)
        if b is None:
            b = _BEACONS[name] = Beacon(name)
        return b


def beacons_snapshot(now: Optional[float] = None) -> dict:
    """{name: {count, age_s}} for every registered process-wide
    beacon — part of every blackbox dump."""
    now = time.monotonic() if now is None else now
    with _BEACONS_MU:
        bs = list(_BEACONS.values())
    out = {}
    for b in bs:
        count, t_last = b.read()
        out[b.name] = {"count": count,
                       "age_s": round(now - t_last, 3)}
    return out


# ---------------------------------------------------------------------------
# declarative rules over MetricsRegistry deltas
# ---------------------------------------------------------------------------

def _metric_values(snapshot: dict, metric: str) -> List[float]:
    """Every series value of ``metric`` across label sets in a
    registry snapshot (counters + gauges tables)."""
    out = []
    for table in ("counters", "gauges"):
        for key, val in snapshot.get(table, {}).items():
            if key.split("{", 1)[0] == metric:
                out.append(float(val))
    return out


def _metric_total(snapshot: dict, metric: str):
    """Sum of every series of ``metric``; None when it has no series
    yet. The right reduction for RATE rules (aggregate throughput)."""
    vals = _metric_values(snapshot, metric)
    return sum(vals) if vals else None


class HealthRule:
    """One declarative check over MetricsRegistry deltas, evaluated
    each watchdog tick. Build via the classmethods:

      - ``rate_above(name, metric, per_s)`` — a counter's windowed
        rate exceeds ``per_s`` (recompile storm, anomaly-skip burn,
        shed burn);
      - ``rate_collapse(name, metric, frac)`` — a counter's windowed
        rate falls below ``frac`` of its rolling (EWMA) baseline after
        the baseline established itself (throughput collapse);
      - ``gauge_above(name, metric, threshold)`` — a gauge crossed a
        line (queue saturation, stall fraction).

    ``severity`` defaults to "degraded": rules are trend detectors;
    the hard "unhealthy" verdicts (and blackbox dumps) come from
    beacon stalls unless a rule opts in.
    """

    def __init__(self, name: str, kind: str, metric: str,
                 threshold: Optional[float] = None,
                 window_s: float = 30.0, frac: float = 0.25,
                 min_rate: float = 1.0, severity: str = "degraded"):
        if kind not in ("rate_above", "rate_collapse", "gauge_above"):
            raise ValueError("unknown HealthRule kind %r" % kind)
        if severity not in SEVERITY_RANK or severity == "healthy":
            raise ValueError("severity must be degraded|unhealthy")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.threshold = threshold
        self.window_s = float(window_s)
        self.frac = float(frac)
        self.min_rate = float(min_rate)
        self.severity = severity
        self._samples: "collections.deque" = collections.deque()
        self._baseline: Optional[float] = None

    @classmethod
    def rate_above(cls, name, metric, per_s, window_s=30.0,
                   severity="degraded"):
        return cls(name, "rate_above", metric, threshold=float(per_s),
                   window_s=window_s, severity=severity)

    @classmethod
    def rate_collapse(cls, name, metric, frac=0.25, window_s=30.0,
                      min_rate=1.0, severity="degraded"):
        return cls(name, "rate_collapse", metric, frac=frac,
                   window_s=window_s, min_rate=min_rate,
                   severity=severity)

    @classmethod
    def gauge_above(cls, name, metric, threshold, severity="degraded"):
        return cls(name, "gauge_above", metric,
                   threshold=float(threshold), severity=severity)

    def _window_rate(self, now: float, value: float):
        self._samples.append((now, value))
        while len(self._samples) > 2 and \
                now - self._samples[0][0] > self.window_s:
            self._samples.popleft()
        t0, v0 = self._samples[0]
        dt = now - t0
        if dt <= 0 or len(self._samples) < 2:
            return None
        return max(0.0, (value - v0) / dt)

    def evaluate(self, snapshot: dict, now: float) -> Optional[dict]:
        """-> problem dict (reason/severity/kind/detail/value) or
        None while this rule holds."""
        if self.kind == "gauge_above":
            # per-series MAX, not sum: the threshold means "any one
            # queue/gauge crossed the line" — N healthy models must
            # not add up to a phantom saturation
            vals = _metric_values(snapshot, self.metric)
            if not vals:
                return None
            value = max(vals)
            if value >= self.threshold:
                return {"reason": self.name, "severity": self.severity,
                        "kind": "gauge_above", "metric": self.metric,
                        "value": value,
                        "detail": "%s=%.6g >= %.6g (worst of %d "
                        "series)" % (self.metric, value,
                                     self.threshold, len(vals))}
            return None
        value = _metric_total(snapshot, self.metric)
        if value is None:
            return None
        rate = self._window_rate(now, value)
        if rate is None:
            return None
        if self.kind == "rate_above":
            if rate > self.threshold:
                return {"reason": self.name, "severity": self.severity,
                        "kind": "rate_above", "metric": self.metric,
                        "value": round(rate, 6),
                        "detail": "%s rate %.3g/s > %.3g/s over %.0fs"
                        % (self.metric, rate, self.threshold,
                           self.window_s)}
            return None
        # rate_collapse: EWMA baseline tracks the achieved rate; a
        # live rate far under an established baseline is the collapse
        baseline = self._baseline
        collapsed = (baseline is not None and baseline >= self.min_rate
                     and rate < self.frac * baseline)
        if not collapsed:
            # don't learn the collapsed rate into the baseline — the
            # rule must keep remembering what "normal" looked like
            self._baseline = rate if baseline is None \
                else 0.8 * baseline + 0.2 * rate
        if collapsed:
            return {"reason": self.name, "severity": self.severity,
                    "kind": "rate_collapse", "metric": self.metric,
                    "value": round(rate, 6),
                    "baseline": round(baseline, 6),
                    "detail": "%s rate %.3g/s < %.0f%% of rolling "
                    "baseline %.3g/s" % (self.metric, rate,
                                         self.frac * 100, baseline)}
        return None


def default_rules() -> List[HealthRule]:
    """The stock rule set ``arm_process`` installs: recompile storm,
    training-throughput collapse, serving queue saturation,
    anomaly-skip burn rate, input-pipeline stall fraction."""
    return [
        HealthRule.rate_above("recompile_storm",
                              "executor_compiles_total",
                              per_s=0.5, window_s=60.0),
        HealthRule.rate_collapse("throughput_collapse",
                                 "executor_steps_total",
                                 frac=0.25, window_s=30.0,
                                 min_rate=1.0),
        HealthRule.gauge_above("queue_saturation",
                               "serving_queue_depth", threshold=256),
        HealthRule.rate_above("anomaly_skip_burn",
                              "guard_skipped_steps", per_s=0.5,
                              window_s=60.0),
        HealthRule.gauge_above("input_bound",
                               "input_stall_fraction", threshold=0.5),
    ]


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

class _Watch:
    """One armed beacon watch. Stall = no count change for
    ``deadline_s`` while ``pending_fn`` (if any) reports work
    outstanding for that whole window."""

    def __init__(self, name, beacon_, deadline_s, pending_fn):
        self.name = name
        self.beacon = beacon_
        self.deadline_s = float(deadline_s)
        self.pending_fn = pending_fn
        count, _ = beacon_.read()
        now = time.monotonic()
        self.last_count = count
        self.last_progress_t = now
        self.pending_since: Optional[float] = None

    def check(self, now: float) -> Optional[dict]:
        count, _ = self.beacon.read()
        if count != self.last_count:
            self.last_count = count
            self.last_progress_t = now
            self.pending_since = None
            return None
        if self.pending_fn is None:
            # unconditional watch: the clock is simply last progress
            stalled_for = now - self.last_progress_t
        else:
            try:
                pending = bool(self.pending_fn())
            except Exception:
                pending = False  # a dying owner must not wedge ticks
            if not pending:
                self.pending_since = None
                return None
            if self.pending_since is None:
                # conservative: the clock starts when pending is
                # first OBSERVED, never retroactively
                self.pending_since = now
            stalled_for = now - max(self.last_progress_t,
                                    self.pending_since)
        if stalled_for < self.deadline_s:
            return None
        return {"reason": "stall:%s" % self.name,
                "severity": "unhealthy", "kind": "stall",
                "watch": self.name, "count": count,
                "stalled_s": round(stalled_for, 3),
                "deadline_s": self.deadline_s,
                "detail": "no progress on %s for %.1fs (deadline "
                "%.1fs) with work pending; count=%d"
                % (self.name, stalled_for, self.deadline_s, count)}

    def snapshot(self, now: float) -> dict:
        count, t_last = self.beacon.read()
        return {"count": count,
                "age_s": round(now - t_last, 3),
                "deadline_s": self.deadline_s,
                "pending_since_s": round(now - self.pending_since, 3)
                if self.pending_since is not None else None}


class Watchdog:
    """The per-role health daemon: a thread that ticks every
    ``interval_s``, checks every armed ``watch`` and ``HealthRule``,
    and on every raise/clear transition emits a ``health`` journal
    event and updates the ``health_state{role,reason}`` gauge. A NEW
    unhealthy problem additionally triggers every attached
    ``FlightRecorder`` (one dump per problem until it clears) and any
    ``on_unhealthy`` callbacks.

    The thread starts lazily with the first watch/rule and is a
    daemon — a watchdog never keeps a process alive."""

    def __init__(self, role: Optional[str] = None,
                 interval_s: float = 0.5,
                 registry_: Optional[MetricsRegistry] = None):
        self.role = role
        self.interval_s = float(interval_s)
        self._reg = registry_ or registry()
        self._mu = threading.Lock()
        self._watches: List[_Watch] = []
        self._rules: List[HealthRule] = []
        self._recorders: List["FlightRecorder"] = []
        self._callbacks: List[Callable[[dict], None]] = []
        self._active: Dict[str, dict] = {}   # reason -> problem
        self._dumped: set = set()            # reasons already dumped
        self._verdict = self._make_verdict([], time.monotonic())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes whole evaluations: check_now is called from the
        # daemon tick AND /healthz handler threads, and rule
        # window/baseline state + the raise/clear diff must never be
        # computed by two threads interleaved
        self._eval_mu = threading.Lock()
        self._tick_count = 0
        self._snap_cache: Optional[dict] = None

    # -- arming -------------------------------------------------------
    def watch(self, name: str, beacon: Optional[Beacon] = None,
              deadline_s: float = 30.0,
              pending_fn: Optional[Callable[[], bool]] = None):
        """Arm a stall watch; returns the handle to pass to
        ``unwatch``. ``beacon`` defaults to the process-wide beacon of
        the same name. A name already armed gets a ``#2``/``#3``
        suffix so two same-named components (two engines hosting model
        "default", say) never shadow each other's stall reason."""
        b = beacon if beacon is not None else globals()["beacon"](name)
        with self._mu:
            taken = {w.name for w in self._watches}
            unique, k = name, 2
            while unique in taken:
                unique = "%s#%d" % (name, k)
                k += 1
            w = _Watch(unique, b, deadline_s, pending_fn)
            self._watches.append(w)
        self._ensure_thread()
        return w

    def unwatch(self, handle):
        with self._mu:
            if handle in self._watches:
                self._watches.remove(handle)

    def add_rule(self, rule: HealthRule):
        with self._mu:
            self._rules.append(rule)
        self._ensure_thread()
        return rule

    def remove_rule(self, rule: HealthRule):
        with self._mu:
            if rule in self._rules:
                self._rules.remove(rule)

    def attach_recorder(self, recorder: "FlightRecorder"):
        with self._mu:
            if recorder not in self._recorders:
                self._recorders.append(recorder)

    def on_unhealthy(self, fn: Callable[[dict], None]):
        with self._mu:
            self._callbacks.append(fn)

    # -- lifecycle ----------------------------------------------------
    def _ensure_thread(self):
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="health-watchdog")
            self._thread.start()

    def start(self):
        self._ensure_thread()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        with self._mu:
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.check_now()
                self._feed_recorders()
            except Exception:
                # the watchdog must never take the process down
                pass

    def _feed_recorders(self):
        """Per-tick black-box feeding: every attached recorder gets a
        metric sample each tick, and a stack capture every ~20 ticks
        (the pre-wedge trajectory a post-mortem dump replays). Daemon
        tick only — /healthz scrapes must not skew the ring cadence."""
        with self._mu:
            recorders = list(self._recorders)
            self._tick_count += 1
            nth = self._tick_count
        if not recorders:
            return
        # reuse the snapshot check_now just took for its rules (one
        # registry walk per tick, not two); rule-less watchdogs still
        # sample fresh
        snap, self._snap_cache = self._snap_cache, None
        if snap is None:
            snap = self._reg.snapshot()
        for rec in recorders:
            rec.sample(snap)
            if nth % 20 == 0:
                rec.capture_stacks()

    # -- evaluation ---------------------------------------------------
    def _make_verdict(self, problems: List[dict], now: float) -> dict:
        worst = "healthy"
        for p in problems:
            if SEVERITY_RANK[p["severity"]] > SEVERITY_RANK[worst]:
                worst = p["severity"]
        return {"state": worst,
                "role": self.role or _journal.get_role(),
                "t_wall": time.time(),
                "problems": list(problems),
                "watches": {w.name: w.snapshot(now)
                            for w in self._watches},
                "rules": [r.name for r in self._rules]}

    def check_now(self, rules: bool = True) -> dict:
        """Run one evaluation synchronously (the /healthz path and
        tests use this; the daemon thread calls it every tick).
        Evaluations are serialized: concurrent scrapes must not
        interleave inside rule window state or double-report a
        raise/clear transition. ``rules=False`` (the /healthz scrape
        path) re-checks only the stall watches and CARRIES the last
        tick's rule verdicts unchanged — rule windows/EWMA baselines
        must adapt at the daemon cadence, not at whatever frequency
        an external scraper happens to probe."""
        with self._eval_mu:
            return self._check_locked(rules)

    def _check_locked(self, rules_fresh: bool = True) -> dict:
        now = time.monotonic()
        with self._mu:
            watches = list(self._watches)
            rules = list(self._rules)
        problems = []
        for w in watches:
            p = w.check(now)
            if p is not None:
                problems.append(p)
        if rules and rules_fresh:
            snap = self._reg.snapshot()
            self._snap_cache = snap  # _feed_recorders reuses it
            for r in rules:
                p = r.evaluate(snap, now)
                if p is not None:
                    problems.append(p)
        elif rules:
            # scrape path: carry the daemon's last rule verdicts
            with self._mu:
                problems.extend(
                    p for p in self._active.values()
                    if p["kind"] != "stall")
        with self._mu:
            previous = self._active
            self._active = {p["reason"]: p for p in problems}
            raised = [p for p in problems
                      if p["reason"] not in previous]
            cleared = [p for r, p in previous.items()
                       if r not in self._active]
            for p in cleared:
                self._dumped.discard(p["reason"])
            verdict = self._make_verdict(problems, now)
            self._verdict = verdict
            recorders = list(self._recorders)
            callbacks = list(self._callbacks)
        role = verdict["role"]
        reg = self._reg
        for p in raised:
            _journal.emit("health", action="raise",
                          reason=p["reason"],
                          severity=p["severity"],
                          problem_kind=p["kind"],
                          detail=p.get("detail"))
            reg.gauge("health_state", role=role,
                      reason=p["reason"]).set(
                SEVERITY_RANK[p["severity"]])
        for p in cleared:
            _journal.emit("health", action="clear",
                          reason=p["reason"],
                          severity=p["severity"],
                          problem_kind=p["kind"])
            reg.gauge("health_state", role=role,
                      reason=p["reason"]).set(0.0)
        reg.gauge("health_state", role=role, reason="overall").set(
            SEVERITY_RANK[verdict["state"]])
        for p in raised:
            if p["severity"] != "unhealthy":
                continue
            with self._mu:
                if p["reason"] in self._dumped:
                    continue
                self._dumped.add(p["reason"])
            for rec in recorders:
                try:
                    rec.dump("watchdog:%s" % p["reason"],
                             extra={"verdict": verdict})
                except Exception:
                    pass
            for cb in callbacks:
                try:
                    cb(p)
                except Exception:
                    pass
        return verdict

    def verdict(self) -> dict:
        """The most recent verdict (no fresh evaluation)."""
        with self._mu:
            return dict(self._verdict)


# ---------------------------------------------------------------------------
# flight recorder (the black box)
# ---------------------------------------------------------------------------

def _capture_stacks() -> List[dict]:
    """All-thread stacks via sys._current_frames — the wedge evidence
    a SIGKILL would otherwise destroy."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append({
            "thread_id": tid,
            "name": names.get(tid, "?"),
            "frames": [ln.rstrip("\n") for ln in
                       traceback.format_stack(frame)],
        })
    return out


class FlightRecorder:
    """Bounded in-memory ring of recent metric samples + periodic
    stack captures, dumped as ``blackbox.<role>.json`` on demand.
    Attach to a ``Watchdog`` (it samples each tick and dumps on a
    stall verdict) and/or ``install_signal_handlers()`` for the
    SIGTERM / fatal-error paths. With no ``dir`` (and no
    ``PADDLE_TPU_BLACKBOX_DIR``) the ring still fills but ``dump``
    is a no-op returning None."""

    def __init__(self, role: Optional[str] = None,
                 dir: Optional[str] = None, capacity: int = 128,
                 stack_history: int = 4,
                 registry_: Optional[MetricsRegistry] = None):
        self.role = role
        self.dir = dir if dir is not None \
            else os.environ.get(ENV_BLACKBOX_DIR) or None
        self._reg = registry_ or registry()
        self._mu = threading.Lock()
        self._samples: "collections.deque" = collections.deque(
            maxlen=int(capacity))
        self._stacks: "collections.deque" = collections.deque(
            maxlen=int(stack_history))
        self._dump_count = 0
        self._reasons: List[str] = []
        self._prev_sigterm = None
        self._prev_excepthook = None
        self._fault_file = None
        self._in_dump = False
        self._signals_installed = False

    def set_dir(self, dir: Optional[str]):
        self.dir = dir
        return self

    # -- sampling -----------------------------------------------------
    def sample(self, snapshot: Optional[dict] = None):
        """Append one metric sample to the ring (the watchdog calls
        this each tick when attached; callers may too)."""
        snap = snapshot if snapshot is not None \
            else self._reg.snapshot()
        lite = {"t_wall": time.time(),
                "counters": snap.get("counters", {}),
                "gauges": snap.get("gauges", {})}
        with self._mu:
            self._samples.append(lite)

    def capture_stacks(self):
        stacks = _capture_stacks()
        with self._mu:
            self._stacks.append({"t_wall": time.time(),
                                 "stacks": stacks})
        return stacks

    # -- dumping ------------------------------------------------------
    def dump_path(self) -> Optional[str]:
        if not self.dir:
            return None
        role = self.role or _journal.get_role()
        return os.path.join(self.dir,
                            "blackbox.%s.json" % role)

    def dump(self, reason: str, extra: Optional[dict] = None,
             path: Optional[str] = None) -> Optional[str]:
        """Write the black box: fresh all-thread stacks, the stack
        history ring, the metric-sample ring + a final registry
        snapshot, the journal tail, beacon ages, and every reason
        this process dumped so far. Atomic (tmp + rename); returns
        the path, or None when no directory is configured."""
        with self._mu:
            if self._in_dump:
                return None  # re-entrant signal during a dump
            self._in_dump = True
        try:
            out = path or self.dump_path()
            if out is None:
                return None
            role = self.role or _journal.get_role()
            with self._mu:
                self._dump_count += 1
                self._reasons.append(reason)
                samples = list(self._samples)
                stack_hist = list(self._stacks)
                count = self._dump_count
                reasons = list(self._reasons)
            box = {
                "role": role,
                "pid": os.getpid(),
                "reason": reason,
                "reasons": reasons,
                "dump_count": count,
                "t_wall": time.time(),
                "t_mono": time.monotonic(),
                "argv": list(sys.argv),
                "stacks": _capture_stacks(),
                "stack_history": stack_hist,
                "beacons": beacons_snapshot(),
                "metrics": self._reg.snapshot(),
                "metric_samples": samples,
                "journal_tail": _journal.events()[-256:],
                "extra": extra or {},
            }
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            tmp = out + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(box, f, default=repr)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, out)
            _journal.emit("blackbox_dump", reason=reason, path=out)
            return out
        finally:
            with self._mu:
                self._in_dump = False

    # -- hooks --------------------------------------------------------
    def install_signal_handlers(self):
        """SIGTERM -> dump then chain to the previous handler (or the
        default die). Additionally registers ``faulthandler`` on
        SIGTERM writing ``blackbox.<role>.stacks.txt``: the
        C-level dump fires even when the main thread is wedged inside
        a C call where no Python handler can run (the observed
        ``jax.devices()`` claim hang). Must be called from the main
        thread; returns False (and does nothing) elsewhere."""
        import signal
        if threading.current_thread() is not threading.main_thread():
            return False
        if getattr(self, "_signals_installed", False):
            # idempotent: repeated arm_process must not chain N dump
            # layers onto one SIGTERM or leak stacks-file handles
            return True
        self._signals_installed = True

        def _on_term(signum, frame):
            # dump on a HELPER thread with a bounded join: the handler
            # runs on the main thread, and if the signal interrupted a
            # frame that holds journal._MU (emit's critical section) a
            # same-thread dump would deadlock on its own lock. The
            # helper blocks instead; on timeout the handler proceeds
            # (the interrupted frame releases the lock once the
            # handler returns, and the daemon helper finishes the dump
            # if the chained handler doesn't exit first).
            try:
                t = threading.Thread(target=self.dump,
                                     args=("SIGTERM",), daemon=True)
                t.start()
                t.join(timeout=10.0)
            except Exception:
                pass
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_IGN:
                return
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _on_term)
        if self.dir:
            try:
                import faulthandler
                os.makedirs(self.dir, exist_ok=True)
                role = self.role or _journal.get_role()
                self._fault_file = open(
                    os.path.join(self.dir,
                                 "blackbox.%s.stacks.txt" % role),
                    "w")
                faulthandler.register(signal.SIGTERM,
                                      file=self._fault_file,
                                      chain=True)
            except Exception:
                pass
        return True

    def install_excepthook(self):
        """Uncaught-exception (fatal error) path: dump, then defer to
        the previous hook."""
        prev = sys.excepthook

        def _hook(tp, val, tb):
            try:
                self.dump("fatal:%s" % getattr(tp, "__name__", tp),
                          extra={"error": repr(val)})
            except Exception:
                pass
            prev(tp, val, tb)

        self._prev_excepthook = prev
        sys.excepthook = _hook
        return True


# ---------------------------------------------------------------------------
# process singletons + /healthz
# ---------------------------------------------------------------------------

# REENTRANT by design: get_watchdog() calls get_recorder() under it,
# and a future accessor / watchdog callback reached from inside one of
# these MUST NOT deadlock the way the CLI path once did (a plain Lock
# here wedged `doctor`-adjacent tooling but never pytest, because
# pytest happened to create the recorder first). Hardened PR 11 —
# regression-tested by test_health.py::TestSingletonReentrancy.
_SINGLETON_MU = threading.RLock()
_WATCHDOG: Optional[Watchdog] = None
_RECORDER: Optional[FlightRecorder] = None


def get_watchdog(role: Optional[str] = None,
                 interval_s: float = 0.5) -> Watchdog:
    """The process-wide watchdog every runtime component arms its
    watches on (created lazily; the singleton recorder is attached so
    stall verdicts leave a black box whenever a dump dir is
    configured)."""
    global _WATCHDOG
    wd = _WATCHDOG
    if wd is not None:
        return wd
    with _SINGLETON_MU:
        if _WATCHDOG is None:
            # safe under the (reentrant) singleton lock — this nested
            # acquisition is exactly the shape that used to deadlock
            rec = get_recorder()
            _WATCHDOG = Watchdog(role=role, interval_s=interval_s)
            _WATCHDOG.attach_recorder(rec)
        return _WATCHDOG


def get_recorder() -> FlightRecorder:
    """The process-wide flight recorder (dump dir from
    ``PADDLE_TPU_BLACKBOX_DIR`` unless ``set_blackbox_dir`` points it
    elsewhere)."""
    global _RECORDER
    rec = _RECORDER
    if rec is not None:
        return rec
    with _SINGLETON_MU:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def set_blackbox_dir(dir: Optional[str]) -> FlightRecorder:
    """Point the singleton recorder's dumps at ``dir`` (tests, tools
    and the launcher use this; env ``PADDLE_TPU_BLACKBOX_DIR`` is the
    fleet-wide way)."""
    return get_recorder().set_dir(dir)


def arm_process(role: Optional[str] = None,
                blackbox_dir: Optional[str] = None,
                rules: bool = True, signals: bool = True,
                excepthook: bool = False):
    """One-call arming for a worker process: role stamp, singleton
    watchdog + default rules, flight recorder (+ SIGTERM hook when on
    the main thread). Returns (watchdog, recorder). Idempotent-ish:
    repeated calls reuse the singletons (rules are only installed
    once)."""
    if role is not None:
        _journal.set_role(role)
    rec = get_recorder()
    if blackbox_dir is not None:
        rec.set_dir(blackbox_dir)
    wd = get_watchdog(role=role)
    if rules and not wd._rules:
        for r in default_rules():
            wd.add_rule(r)
    if signals:
        rec.install_signal_handlers()
    if excepthook:
        rec.install_excepthook()
    wd.start()
    return wd, rec


# the control plane (observability/control.py) registers its
# control_block() here so /healthz can show WHAT IS ACTING on this
# process next to what is being watched — armed policies, recent
# ledger entries, suppression counts
_CONTROL_PROVIDER: Optional[Callable[[], dict]] = None


def register_control_provider(fn: Optional[Callable[[], dict]]):
    """Install (or with ``None`` clear) the callable whose dict lands
    in the ``control`` block of every ``healthz()`` payload."""
    global _CONTROL_PROVIDER
    _CONTROL_PROVIDER = fn
    return fn


def _attach_control(verdict: dict) -> dict:
    prov = _CONTROL_PROVIDER
    if prov is not None:
        try:
            verdict["control"] = prov()
        except Exception:
            verdict["control"] = {"error": "control provider raised"}
    return verdict


def healthz():
    """The ``GET /healthz`` payload: (http_status, verdict_dict).
    200 while healthy/degraded (degraded is advisory — the process is
    making progress), 503 on an unhealthy verdict, and 200/"unknown"
    when no watchdog was ever armed in this process (nothing is
    watching, which is itself worth surfacing to the scraper). When a
    control plane is armed the payload grows a ``control`` block
    (armed policies, recent actions, suppressions)."""
    wd = _WATCHDOG
    if wd is None:
        return 200, _attach_control(
            {"state": "unknown",
             "role": _journal.get_role(),
             "detail": "no watchdog armed in this process"})
    # rules=False: a scrape re-checks the stall watches (cheap,
    # idempotent) but must not feed rule windows/baselines — external
    # probe frequency must never change detection sensitivity
    v = _attach_control(wd.check_now(rules=False))
    return (503 if v["state"] == "unhealthy" else 200), v
