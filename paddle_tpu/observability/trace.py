"""Cross-process trace correlation: trace/span ids that ride the RPC
wire so a pserver's handler span links back to the trainer span that
caused it.

Model (w3c-traceparent shaped, minimal): a TRACE id names one causal
chain (e.g. one training step's communication phase); each unit of
work inside it is a SPAN with its own id and an optional parent span.
``span(name)`` opens a profiler ``RecordEvent`` carrying
``trace``/``span``/``parent_span`` args (visible in the chrome trace's
args panel) and installs the context in a thread-local stack, so
nested spans and RPC calls issued inside it inherit the trace.

Wire format: ``pack_wire_name`` appends a 4th ``@@``-delimited field
``<trace>-<span>`` next to ``@@tid@@seq`` (rpc.py); the server tags
its ``rpc_server:<VERB>`` span with the inbound ids. Spans are only
recorded while the profiler is enabled (RecordEvent's no-op contract),
so the steady-state RPC hot path pays nothing.

``tools/trace_merge.py`` merges the per-process chrome traces into one
timeline (clock offsets estimated from heartbeat RTT journal events)
and draws flow arrows between client and server spans sharing a trace
id.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional, Tuple

__all__ = ["span", "attach", "current_span", "new_trace_id",
           "new_span_id", "wire_token", "parse_wire_token"]

_tls = threading.local()


def new_trace_id() -> str:
    return os.urandom(8).hex()


def new_span_id() -> str:
    return os.urandom(4).hex()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, span_id) of the innermost open span on this thread,
    or (None, None)."""
    st = _stack()
    return st[-1] if st else (None, None)


@contextlib.contextmanager
def span(name: str, args: Optional[dict] = None,
         trace: Optional[str] = None):
    """Open a correlated span: a profiler RecordEvent named ``name``
    whose args carry trace/span/parent ids. ``trace`` forces the trace
    id (servers adopt the inbound one); otherwise the enclosing span's
    trace is inherited, or a fresh one is minted."""
    from .. import profiler as _profiler
    parent_trace, parent_span = current_span()
    trace_id = trace or parent_trace or new_trace_id()
    span_id = new_span_id()
    a = dict(args or {})
    a["trace"] = trace_id
    a["span"] = span_id
    if parent_span is not None and trace is None:
        a["parent_span"] = parent_span
    st = _stack()
    st.append((trace_id, span_id))
    try:
        with _profiler.RecordEvent(name, args=a):
            yield trace_id, span_id
    finally:
        st.pop()


@contextlib.contextmanager
def attach(context: Tuple[Optional[str], Optional[str]]):
    """Adopt an existing (trace_id, span_id) as this thread's current
    span — the hand-off for work crossing a thread-pool boundary
    (e.g. the PS runtime's per-endpoint workers), where thread-local
    context does not follow the task."""
    if not context or context[0] is None:
        yield
        return
    st = _stack()
    st.append((context[0], context[1]))
    try:
        yield
    finally:
        st.pop()


def wire_token(trace_id: Optional[str],
               span_id: Optional[str]) -> Optional[str]:
    """Encode (trace, span) for the RPC name field; None when there is
    nothing to carry."""
    if not trace_id:
        return None
    return "%s-%s" % (trace_id, span_id or "")


def parse_wire_token(tok: Optional[str]):
    """Inverse of wire_token -> (trace_id|None, span_id|None)."""
    if not tok or "-" not in tok:
        return None, None
    trace_id, span_id = tok.split("-", 1)
    return trace_id or None, span_id or None
