"""Lightweight Prometheus-text ``/metrics`` export thread.

``start_metrics_server(port=0)`` binds a daemon ThreadingHTTPServer
serving:

  - ``GET /metrics``  -> ``registry().prometheus_text()`` (text/plain
    version 0.0.4 — scrapeable by any Prometheus/agent);
  - ``GET /journal``  -> the in-memory event ring as JSON (newest
    last) — a poor-man's debug endpoint for seam debugging;
  - ``GET /healthz``  -> the health plane's machine-readable verdict
    (health.healthz()): JSON body with state/problems/watches, 200
    while healthy/degraded (or "unknown" when no watchdog is armed),
    503 on an unhealthy verdict — a scraper or LB can act on it.

Usable by serving engines (``ServingEngine(metrics_port=...)``) and
pservers (``PServerRuntime(metrics_port=...)``) or standalone; one
server per process is the intended shape (the registry is
process-wide)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import health as _health
from . import journal as _journal
from .registry import registry

__all__ = ["MetricsServer", "start_metrics_server"]


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — http.server contract
        path = self.path.split("?", 1)[0]
        code = 200
        if path == "/metrics":
            body = registry().prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/journal":
            body = json.dumps(_journal.events(),
                              default=repr).encode()
            ctype = "application/json"
        elif path == "/healthz":
            code, verdict = _health.healthz()
            body = (json.dumps(verdict, default=repr) + "\n").encode()
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # scrapes must not spam stderr
        pass


class MetricsServer:
    """Owns the HTTP server + its serve thread; ``stop()`` to close."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = "http://%s:%d" % (host, self.port)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="obs-metrics-%d" % self.port)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def start_metrics_server(port: int = 0,
                         host: str = "127.0.0.1") -> MetricsServer:
    return MetricsServer(port=port, host=host)
