"""Unified telemetry plane: metrics registry, structured event
journal, and cross-process trace correlation.

Reference analog: the reference dedicates a platform layer to
observability (paddle/fluid/platform/profiler.{h,cc}); ``profiler.py``
reproduced the RAII-span + chrome-trace piece, and this package is the
rest — the one place the runtime's previously-disconnected telemetry
islands (profiler counters, serving ``EngineStats``, executor
compile/dispatch counts, RPC reconnects, guard skip counters,
prefetcher stall stats, pserver runtime events) route through:

  - **registry.py** — process-wide ``MetricsRegistry`` (labeled
    counters/gauges/histograms, lock-cheap hot path), exported as
    Prometheus text by **export.py**'s ``/metrics`` thread;
  - **journal.py** — ``emit(kind, **fields)`` structured events with
    wall+monotonic time, pid/role, per-process seq, and an optional
    JSONL sink per process (the launcher stamps one per worker);
  - **trace.py** — trace/span ids that ride the RPC wire next to
    ``@@tid@@seq`` so pserver handler spans link to the trainer spans
    that caused them; ``tools/trace_merge.py`` merges per-process
    chrome traces into one timeline.

See docs/observability.md for the schema and walkthroughs.
"""

from __future__ import annotations

import contextlib

from .control import (ControlPlane, RemediationPolicy,  # noqa: F401
                      ScalingPolicy)
from .export import MetricsServer, start_metrics_server  # noqa: F401
from .health import (Beacon, FlightRecorder, HealthRule,  # noqa: F401
                     Watchdog, arm_process, beacon,
                     beacons_snapshot, default_rules, get_recorder,
                     get_watchdog, healthz,
                     register_control_provider, set_blackbox_dir)
from .journal import (clear as clear_journal,  # noqa: F401
                      configure as configure_journal,
                      emit, events as journal_events, get_role,
                      read_journal, set_role)
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, registry)
from .trace import (attach, current_span, new_span_id,  # noqa: F401
                    new_trace_id, parse_wire_token, span, wire_token)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "registry",
    "emit", "journal_events", "clear_journal", "configure_journal",
    "read_journal", "set_role", "get_role",
    "span", "attach", "current_span", "new_trace_id", "new_span_id",
    "wire_token", "parse_wire_token",
    "MetricsServer", "start_metrics_server", "disabled",
    "Beacon", "beacon", "beacons_snapshot", "HealthRule", "Watchdog",
    "FlightRecorder", "get_watchdog", "get_recorder",
    "set_blackbox_dir", "arm_process", "default_rules", "healthz",
    "register_control_provider",
    "ControlPlane", "RemediationPolicy", "ScalingPolicy",
]


@contextlib.contextmanager
def disabled():
    """Stub the whole telemetry plane (registry mutations + journal
    emits become no-ops) for the duration — the baseline the
    ``telemetry_overhead`` bench row measures against. Spans/profiler
    behavior is unchanged (already gated on the profiler switch)."""
    from . import journal as _journal
    reg = registry()
    prev_reg, prev_j = reg.enabled, _journal._ENABLED
    reg.set_enabled(False)
    _journal.set_enabled(False)
    try:
        yield
    finally:
        reg.set_enabled(prev_reg)
        _journal.set_enabled(prev_j)
