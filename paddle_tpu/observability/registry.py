"""Process-wide metrics registry: labeled counters / gauges /
histograms with a lock-cheap hot path.

Reference analog: the reference scatters scalar accounting across
subsystems (profiler counters, serving EngineStats, executor compile
counts, RPC reconnect tallies, guard skip counters). This registry is
the ONE store they all route through while keeping their existing
public APIs — so ``tools/obs_dump.py``, the Prometheus ``/metrics``
exporter (export.py), and ``Executor.telemetry()`` see a single
consistent view of the process.

Cost model: a bump is one dict-free attribute path — callers hold the
metric object (``registry().counter(name)`` memoizes), and ``inc`` is
one lock acquire + one float add, exactly what the old
``profiler.bump_counter`` paid. Metric CREATION takes the registry
lock; steady-state mutation takes only the metric's own lock.

``registry().set_enabled(False)`` (or ``observability.disabled()``)
turns every mutation into a no-op — the stub the
``telemetry_overhead`` bench row compares against.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry"]

# default histogram buckets: seconds-scaled (covers sub-ms device
# dispatches through multi-second compiles)
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _label_key(labels: Dict[str, object]) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "metric"

    def __init__(self, name: str, labels: Dict[str, str], reg):
        self.name = name
        self.labels = dict(labels)
        self._reg = reg
        self._mu = threading.Lock()

    def _on(self) -> bool:
        return self._reg._enabled

    def label_str(self) -> str:
        return _labels_str(self.labels)


class Counter(_Metric):
    """Monotonic accumulator (resettable for tests/bench probes)."""

    kind = "counter"

    def __init__(self, name, labels, reg):
        super().__init__(name, labels, reg)
        self._v = 0.0

    def inc(self, value: float = 1.0):
        if not self._on():
            return
        with self._mu:
            self._v += float(value)

    @property
    def value(self) -> float:
        with self._mu:
            return self._v

    def reset(self):
        with self._mu:
            self._v = 0.0


class Gauge(_Metric):
    """Last-write-wins scalar (queue depth, stall fraction, ...)."""

    kind = "gauge"

    def __init__(self, name, labels, reg):
        super().__init__(name, labels, reg)
        self._v = 0.0

    def set(self, value: float):
        if not self._on():
            return
        with self._mu:
            self._v = float(value)

    def inc(self, value: float = 1.0):
        if not self._on():
            return
        with self._mu:
            self._v += float(value)

    @property
    def value(self) -> float:
        with self._mu:
            return self._v

    def reset(self):
        with self._mu:
            self._v = 0.0


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus classic shape): per-bucket
    counts + running sum/count. ``observe`` is one bisect + three adds
    under the metric lock."""

    kind = "histogram"

    def __init__(self, name, labels, reg, buckets=None):
        super().__init__(name, labels, reg)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        if not self._on():
            return
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        with self._mu:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        with self._mu:
            return self._count

    @property
    def sum(self) -> float:
        with self._mu:
            return self._sum

    def snapshot(self) -> dict:
        with self._mu:
            counts = list(self._counts)
            total, s = self._count, self._sum
        return {"buckets": list(self.buckets), "counts": counts,
                "count": total, "sum": s,
                "mean": (s / total) if total else None}

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-upper-bound estimate of the q-quantile (the usual
        Prometheus-side histogram_quantile approximation)."""
        snap = self.snapshot()
        total = snap["count"]
        if not total:
            return None
        target = q * total
        acc = 0
        for ub, c in zip(list(self.buckets) + [float("inf")],
                         snap["counts"]):
            acc += c
            if acc >= target:
                return ub
        return float("inf")

    def reset(self):
        with self._mu:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


class MetricsRegistry:
    """Keyed store of metrics; one process-wide instance via
    ``registry()`` (private instances allowed for tests)."""

    _KINDS = {"counter": Counter, "gauge": Gauge,
              "histogram": Histogram}

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: Dict[Tuple, _Metric] = {}
        self._enabled = True

    # -- creation/lookup (memoized; hot callers keep the object) ------
    def _get(self, kind, name, labels, **kw):
        key = (kind, name, _label_key(labels))
        m = self._metrics.get(key)
        if m is not None:
            return m
        with self._mu:
            m = self._metrics.get(key)
            if m is None:
                existing_kind = next(
                    (k for (k, n, lk), _ in self._metrics.items()
                     if n == name and k != kind), None)
                if existing_kind is not None:
                    raise ValueError(
                        "metric %r already registered as a %s"
                        % (name, existing_kind))
                m = self._KINDS[kind](name, labels, self, **kw)
                self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, buckets=None, **labels) -> Histogram:
        return self._get("histogram", name, labels, buckets=buckets)

    def remove_series(self, name: str, **labels):
        """Drop one labeled series (any kind) from the export set —
        for per-instance series whose instance is gone for good (a
        scaled-down replica's queue-depth gauge): under churn, zeroing
        alone leaves the registry growing one dead series per retired
        instance forever. The detached metric object stays safe to
        write; it just no longer exports."""
        lk = _label_key(labels)
        with self._mu:
            for kind in self._KINDS:
                self._metrics.pop((kind, name, lk), None)

    # -- enable/disable (the bench stub) ------------------------------
    def set_enabled(self, on: bool):
        self._enabled = bool(on)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- reducing -----------------------------------------------------
    def _sorted(self):
        with self._mu:
            ms = list(self._metrics.values())
        return sorted(ms, key=lambda m: (m.name, _label_key(m.labels)))

    def snapshot(self) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}}
        keyed by ``name{label="v",...}``."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self._sorted():
            key = m.name + m.label_str()
            if m.kind == "counter":
                out["counters"][key] = m.value
            elif m.kind == "gauge":
                out["gauges"][key] = m.value
            else:
                out["histograms"][key] = m.snapshot()
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every metric.
        Served by ``observability.start_metrics_server``."""
        lines = []
        seen_type = set()
        for m in self._sorted():
            name = _prom_name(m.name)
            if name not in seen_type:
                seen_type.add(name)
                lines.append("# TYPE %s %s" % (name, m.kind))
            if m.kind in ("counter", "gauge"):
                lines.append("%s%s %s"
                             % (name, m.label_str(), _fmt(m.value)))
                continue
            snap = m.snapshot()
            acc = 0
            base = dict(m.labels)
            for ub, c in zip(snap["buckets"] + [float("inf")],
                             snap["counts"]):
                acc += c
                lab = dict(base)
                lab["le"] = "+Inf" if ub == float("inf") else _fmt(ub)
                lines.append("%s_bucket%s %d"
                             % (name, _labels_str(lab), acc))
            lines.append("%s_sum%s %s" % (name, m.label_str(),
                                          _fmt(snap["sum"])))
            lines.append("%s_count%s %d" % (name, m.label_str(),
                                            snap["count"]))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Drop every metric (tests). Live handles callers memoized
        keep mutating their detached objects harmlessly."""
        with self._mu:
            self._metrics = {}


def _prom_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalnum() or ch in "_:"
        if ok and ch.isdigit() and i == 0:
            ok = False
        out.append(ch if ok else "_")
    return "".join(out)


def _labels_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, str(v).replace('"', r'\"'))
        for k, v in sorted(labels.items()))


def _fmt(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every telemetry island routes
    through."""
    return _REGISTRY
