"""Structured event journal: one ``emit(kind, **fields)`` for every
discrete runtime event, with an optional per-process JSONL sink.

Every event carries wall + monotonic timestamps, the process id, a
ROLE tag (``trainer-0`` / ``pserver-1`` / ``serving`` — stamped by
``set_role`` or the ``PADDLE_TPU_ROLE`` env the launcher writes), and
a per-process monotonic sequence number, so fleet logs from N
processes merge into one causally-ordered timeline
(``tools/obs_dump.py`` / ``tools/trace_merge.py``).

Producers routed through here: ``PServerRuntime``/``ListenAndServ``
events (snapshot, trainer_evicted, dup_send_ignored, ...),
``GuardedTrainer`` rollback/retry/abort, ``CheckpointSaver``
publish/prune, serving ``server_overloaded``/``batcher_died``,
executor recompiles, RPC reconnects, and heartbeat RTT samples (the
clock-offset raw material for cross-process trace merge).

The sink is configured per process: ``configure(path)`` or the
``PADDLE_TPU_EVENT_JOURNAL`` env var (checked lazily on first emit —
the launcher stamps one path per worker). Events are also kept in a
bounded in-memory ring readable via ``events()``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import List, Optional

__all__ = ["emit", "events", "clear", "configure", "set_role",
           "get_role", "read_journal"]

_MU = threading.Lock()
_RING: "collections.deque" = collections.deque(maxlen=4096)
_SEQ = 0
_ROLE: Optional[str] = None
_SINK = None
_SINK_PATH: Optional[str] = None
_SINK_MAX_BYTES: Optional[int] = None
_SINK_BYTES = 0
_ENV_CHECKED = False
_ENABLED = True

ENV_JOURNAL = "PADDLE_TPU_EVENT_JOURNAL"
ENV_JOURNAL_MAX_BYTES = "PADDLE_TPU_EVENT_JOURNAL_MAX_BYTES"
ENV_ROLE = "PADDLE_TPU_ROLE"
ROTATED_SUFFIX = ".1"


def set_role(role: Optional[str]):
    """Stamp this process's role (``trainer-k`` / ``pserver-j`` /
    ``serving``); None reverts to the env/pid default."""
    global _ROLE
    with _MU:
        _ROLE = role


def get_role() -> str:
    role = _ROLE or os.environ.get(ENV_ROLE)
    return role if role else "pid-%d" % os.getpid()


def configure(path: Optional[str] = None, capacity: Optional[int] = None,
              max_bytes: Optional[int] = None):
    """Set (or with ``path=None`` close) the JSONL sink; optionally
    resize the in-memory ring. ``max_bytes`` arms keep-one size-based
    rotation: when the sink file exceeds it, it is renamed to
    ``<path>.1`` (replacing any previous ``.1``) and a fresh file is
    opened — long fleet runs can't grow the journal unboundedly, and
    ``read_journal`` stitches the rotated file back in. Returns the
    active sink path."""
    global _SINK, _SINK_PATH, _RING, _ENV_CHECKED
    global _SINK_MAX_BYTES, _SINK_BYTES
    with _MU:
        _ENV_CHECKED = True  # explicit config wins over the env var
        if _SINK is not None:
            try:
                _SINK.close()
            except Exception:
                pass
            _SINK, _SINK_PATH = None, None
        _SINK_MAX_BYTES = int(max_bytes) if max_bytes else None
        if path:
            _open_sink_locked(path)
        if capacity is not None:
            _RING = collections.deque(_RING, maxlen=int(capacity))
        return _SINK_PATH


def _open_sink_locked(path):
    """Open the JSONL sink (caller holds _MU): line-buffered append —
    each event is one durable-ish line, and a crashed process leaves
    at worst one torn tail line (read_journal skips it)."""
    global _SINK, _SINK_PATH, _SINK_BYTES
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    _SINK = open(path, "a", buffering=1)
    _SINK_PATH = path
    try:
        _SINK_BYTES = os.path.getsize(path)
    except OSError:
        _SINK_BYTES = 0


def _rotate_sink_locked():
    """Keep-one rotation (caller holds _MU): the full file becomes
    ``<path>.1`` and a fresh sink opens at the same path. Rotation
    failures (exotic filesystems) degrade to append-forever rather
    than crash an emitter."""
    global _SINK, _SINK_MAX_BYTES
    path = _SINK_PATH
    try:
        _SINK.close()
    except Exception:
        pass
    _SINK = None
    try:
        os.replace(path, path + ROTATED_SUFFIX)
    except OSError:
        # a filesystem that cannot rename would otherwise re-trigger
        # rotation (close+rename+open) on EVERY subsequent emit, since
        # the reopened file is still over the bound — disarm and
        # append forever, as documented
        _SINK_MAX_BYTES = None
    _open_sink_locked(path)


def sink_path() -> Optional[str]:
    _check_env()
    return _SINK_PATH


def _check_env():
    """First-emit lazy pickup of the launcher-stamped journal path."""
    global _ENV_CHECKED, _SINK_MAX_BYTES
    if _ENV_CHECKED:
        return
    with _MU:
        if _ENV_CHECKED:
            return
        _ENV_CHECKED = True
        path = os.environ.get(ENV_JOURNAL)
        try:
            mb = int(os.environ.get(ENV_JOURNAL_MAX_BYTES, "0"))
        except ValueError:
            mb = 0
        if mb > 0:
            _SINK_MAX_BYTES = mb
        if path:
            _open_sink_locked(path)


def set_enabled(on: bool):
    global _ENABLED
    _ENABLED = bool(on)


def emit(kind: str, **fields) -> Optional[dict]:
    """Record one structured event; returns it (None while disabled).
    ``fields`` must be JSON-able-ish (non-serializable values degrade
    to repr in the sink, never crash the caller)."""
    global _SEQ
    if not _ENABLED:
        return None
    _check_env()
    ev = dict(fields)
    # core keys win over caller fields — the schema is the contract
    ev.update(kind=str(kind), t_wall=time.time(),
              t_mono=time.monotonic(), pid=os.getpid(),
              role=get_role())
    # ONE critical section for seq assignment + ring/sink append, so
    # the journal's on-disk order IS its seq (causal) order even under
    # concurrent emitters
    global _SINK_BYTES
    with _MU:
        _SEQ += 1
        ev["seq"] = _SEQ
        _RING.append(ev)
        if _SINK is not None:
            try:
                line = json.dumps(ev, default=repr) + "\n"
                _SINK.write(line)
                _SINK_BYTES += len(line)
                if _SINK_MAX_BYTES is not None \
                        and _SINK_BYTES > _SINK_MAX_BYTES:
                    _rotate_sink_locked()
            except Exception:
                pass  # a full disk must not take training down
    return ev


def events(kind: Optional[str] = None,
           since_seq: int = 0) -> List[dict]:
    """In-memory ring view, oldest first; filter by ``kind`` and/or
    strictly-greater ``since_seq``."""
    with _MU:
        evs = list(_RING)
    return [e for e in evs
            if (kind is None or e["kind"] == kind)
            and e["seq"] > since_seq]


def clear():
    """Drop the in-memory ring (the sink file is untouched). The
    per-process seq counter is NOT rewound: a configured sink may
    already hold events with higher seqs, and the on-disk contract is
    that seq order IS causal order for the life of the process."""
    with _MU:
        _RING.clear()


def read_journal(path: str, include_rotated: bool = True) -> List[dict]:
    """Parse one JSONL journal file; malformed lines (torn tail of a
    killed process) are skipped, not fatal. When a rotated sibling
    (``<path>.1``, size-based keep-one rotation) exists it is
    stitched in FIRST, so callers see one contiguous seq-ordered
    stream."""
    out = []
    paths = [path]
    if include_rotated and os.path.exists(path + ROTATED_SUFFIX):
        paths.insert(0, path + ROTATED_SUFFIX)
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out
