"""PyReader / DataLoader: decoupled async host→device feeding
(reference: python/paddle/fluid/reader.py PyReader:45 — python
generators pump a C++ LoDTensorBlockingQueue consumed by reader ops;
buffered_reader double-buffers to device).

TPU-native shape: a background thread runs the user generator and
*pre-transfers* each batch to device (jax.device_put) while the current
step computes — the double-buffer-to-device pattern of the reference's
buffered_reader (operators/reader/buffered_reader.cc) without reader
ops, since the executor takes feeds directly."""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional, Sequence

import time

import numpy as np

from . import profiler as _profiler
from .core.enforce import InvalidArgumentError, enforce
from .data_feeder import DataFeeder

__all__ = ["PyReader", "DataLoader", "DevicePrefetcher"]

_SENTINEL = object()


def _bounded_put(q, stop, item) -> bool:
    """Bounded put that aborts when the consumer went away (a stop
    event was set) — checked BEFORE every attempt, so a producer
    finishing work after shutdown can never enqueue. Shared by
    DevicePrefetcher, PyReader, and reader.decorator.buffered: ONE
    copy of the put/stop contract."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def stack_batches(batches):
    """Stack per-step feed dicts along a NEW leading axis — THE
    ``[K, *batch_shape]`` chunk format ``Executor.run_pipelined``
    consumes. Single source of the format contract: used by
    ``DevicePrefetcher``, ``DatasetBase.chunk_iterator``, and the
    pipeline probe."""
    keys = batches[0].keys()
    for b in batches[1:]:
        if b.keys() != keys:
            raise InvalidArgumentError(
                "prefetch chunk mixes feed keys %s vs %s — the "
                "batch stream must be homogeneous"
                % (sorted(keys), sorted(b.keys())))
    return {k: np.stack([np.asarray(b[k]) for b in batches])
            for k in keys}


def _prefetch_build_chunk(buf, device_put, counters, lock):
    t0 = time.perf_counter()
    with _profiler.RecordEvent("chunk_h2d_overlap",
                               args={"steps": len(buf)}):
        chunk = stack_batches(buf)
        if device_put:
            import jax
            chunk = {k: jax.device_put(v) for k, v in chunk.items()}
            # materialize the transfer ON THIS THREAD so the
            # consumer's get() never pays a lazy copy
            for v in chunk.values():
                v.block_until_ready()
    dt = time.perf_counter() - t0
    with lock:
        counters["h2d_s"] += dt
    _profiler.bump_counter("chunk_h2d_s", dt)
    # health-plane progress: a silent prefetch beacon while the
    # consumer stalls is the "input pipeline wedged" signature the
    # flight recorder / doctor read (module-level beacon on purpose —
    # the pump thread must hold no reference to the prefetcher)
    from .observability import beacon as _beacon
    _beacon("prefetch_chunks").bump()
    return chunk


def _prefetch_pump(it, chunk_size, device_put, q, stop, err, counters,
                   lock):
    """DevicePrefetcher's producer body. Module-level on purpose: the
    thread must reference the queue/event/counters, never the
    prefetcher itself, so an abandoned prefetcher can be collected
    (its finalizer sets ``stop``, which retires this thread)."""
    buf = []
    try:
        for feed in it:
            if stop.is_set():
                return
            buf.append(feed)
            if len(buf) == chunk_size:
                if not _bounded_put(
                        q, stop,
                        (_prefetch_build_chunk(buf, device_put,
                                               counters, lock),
                         len(buf))):
                    return
                buf = []
        if buf and not stop.is_set():
            # ragged tail chunk: fewer steps, one extra compile
            _bounded_put(q, stop,
                          (_prefetch_build_chunk(buf, device_put,
                                                 counters, lock),
                           len(buf)))
    except BaseException as e:  # surfaces in the consumer
        err.append(e)
    finally:
        _bounded_put(q, stop, _SENTINEL)


class DevicePrefetcher:
    """Host-side chunk builder feeding ``Executor.run_pipelined``:
    pulls per-step feed dicts from ``batches``, stacks every
    ``chunk_size`` of them along a NEW leading axis, and
    ``jax.device_put``s the stacked chunk on a background thread while
    the consumer's current chunk is still running on-device — the
    double/triple-buffer-to-device pattern of the reference's
    buffered_reader (operators/reader/buffered_reader.cc), lifted from
    one batch to one scan chunk.

    Iterating yields ``(chunk_dict, n_steps)``; the final chunk may
    hold fewer than ``chunk_size`` batches (one extra compile for the
    tail shape). ``depth`` chunks may be staged in the queue at once
    (2 = double buffering); budget device memory for up to
    ``depth + 2`` live chunks — the staged ones, plus the one the
    producer is mid-``device_put`` on, plus the one the consumer
    holds. A generator exception propagates to the consumer on the
    next ``__next__``; ``close()`` (or exiting the ``with`` block, or
    abandoning the iterator) retires the thread without it pinning
    the staged device chunks forever.

    Stall accounting: time the consumer spent blocked in ``__next__``
    waiting for the host is the input-pipeline **stall** — the device
    had no fresh chunk to run. ``stats()`` reports it as a fraction of
    the consumer's wall time (also bumped into the profiler counters
    ``input_stall_s`` / ``chunk_h2d_s``)."""

    def __init__(self, batches, chunk_size: int, depth: int = 2,
                 device_put: bool = True):
        enforce(chunk_size >= 1, "chunk_size must be >= 1")
        enforce(depth >= 1, "prefetch depth must be >= 1")
        self.chunk_size = int(chunk_size)
        self.depth = int(depth)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._err: List[BaseException] = []
        self._lock = threading.Lock()
        # producer-side counters live in a plain dict shared with the
        # pump thread, NOT attributes: the thread must hold no
        # reference to self (see the finalizer below)
        self._c = {"chunks": 0, "steps": 0, "stall_s": 0.0,
                   "h2d_s": 0.0}
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._done = False
        # the pump closes over the queue/event/counters only — a
        # bound-method target would pin self alive for the thread's
        # lifetime and defeat abandonment cleanup
        self._thread = threading.Thread(
            target=_prefetch_pump,
            args=(iter(batches), self.chunk_size, device_put,
                  self._q, self._stop, self._err, self._c,
                  self._lock),
            daemon=True)
        # a consumer that drops the prefetcher without close()/with
        # must not leak the producer thread + `depth` device chunks:
        # GC of this object trips the stop event (the finalizer holds
        # the EVENT, not self, so it doesn't pin the prefetcher)
        import weakref
        self._finalizer = weakref.finalize(self, self._stop.set)
        self._thread.start()

    # -- consumer side -----------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        if self._t_first is None:
            self._t_first = t0
        item = self._q.get()
        now = time.perf_counter()
        stall = now - t0
        with self._lock:
            self._c["stall_s"] += stall
        _profiler.bump_counter("input_stall_s", stall)
        self._t_last = now
        if item is _SENTINEL:
            self._done = True
            if self._err:
                raise self._err[0]
            raise StopIteration
        chunk, n = item
        with self._lock:
            self._c["chunks"] += 1
            self._c["steps"] += n
        return chunk, n

    def close(self):
        """Retire the producer: unblock its put, drain staged chunks,
        join. Idempotent; safe mid-iteration (break / exception)."""
        self._stop.set()

        def _drain():
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    return

        _drain()
        self._thread.join(timeout=5)
        # registry gauge: the pass's final stall fraction, next to the
        # input_stall_s / chunk_h2d_s counters bump_counter maintains
        frac = self.stats().get("stall_fraction")
        if frac is not None:
            from .observability import registry as _registry
            _registry().gauge("input_stall_fraction").set(frac)
        # drain AGAIN after the join: a producer that was mid-put when
        # the first drain emptied the queue can land one final
        # device-resident chunk, which would stay pinned in device
        # memory for the prefetcher's lifetime (stats() keeps the
        # object alive past the with-block). A join TIMEOUT (producer
        # stuck in a slow device_put) is still leak-free: _put checks
        # the stop event before every put attempt, so a producer that
        # finishes building after this point can never enqueue.
        _drain()
        self._done = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self) -> dict:
        """{chunks, steps, stall_s, h2d_s, elapsed_s, stall_fraction}.
        stall_fraction = consumer wait / consumer wall — the share of
        the training loop's time the device had no data to run.
        ``chunks``/``steps`` count CONSUMED chunks; ``h2d_s`` is
        producer-side and includes staged chunks discarded at
        close(), so on an early-abandoned run h2d_s/chunks overstates
        per-chunk transfer cost by up to (depth+1)x."""
        with self._lock:
            elapsed = ((self._t_last - self._t_first)
                       if self._t_first is not None
                       and self._t_last is not None else 0.0)
            return {
                "chunks": self._c["chunks"],
                "steps": self._c["steps"],
                "chunk_size": self.chunk_size,
                "depth": self.depth,
                "stall_s": round(self._c["stall_s"], 6),
                "h2d_s": round(self._c["h2d_s"], 6),
                "elapsed_s": round(elapsed, 6),
                "stall_fraction": round(
                    self._c["stall_s"] / elapsed, 4) if elapsed > 0
                else None,
            }


class PyReader:
    """Iterable reader bound to a list of feed Variables.

    Usage (iterable mode, the post-1.6 idiom):
        reader = PyReader(feed_list=[img, label], capacity=4)
        reader.decorate_sample_list_generator(batched_creator)
        for data in reader():          # data is a feed dict
            exe.run(main, feed=data, fetch_list=[...])
    """

    def __init__(self, feed_list: Sequence, capacity: int = 2,
                 return_device_arrays: bool = True):
        enforce(capacity >= 1, "capacity must be >= 1")
        self.feed_list = list(feed_list)
        self.capacity = capacity
        self.return_device_arrays = return_device_arrays
        self._feeder = DataFeeder(self.feed_list)
        self._creator: Optional[Callable] = None
        self._mode = None

    # -- decorators (reference reader.py:45 API surface) -------------------
    def decorate_sample_list_generator(self, creator):
        """creator() yields lists of row-tuples (one list = one batch)."""
        self._creator = creator
        self._mode = "sample_list"
        return self

    def decorate_batch_generator(self, creator):
        """creator() yields ready feed dicts or tuples of arrays."""
        self._creator = creator
        self._mode = "batch"
        return self

    def decorate_paddle_reader(self, creator):  # fluid-compat alias
        return self.decorate_sample_list_generator(creator)

    # -- iteration ---------------------------------------------------------
    def _to_feed_dict(self, item):
        if self._mode == "sample_list":
            return self._feeder.feed(item)
        if isinstance(item, dict):
            return item
        enforce(isinstance(item, (list, tuple)) and
                len(item) == len(self.feed_list),
                "batch generator must yield dicts or one array per "
                "feed var")
        return {v.name: a for v, a in zip(self.feed_list, item)}

    def _device_put(self, feed):
        if not self.return_device_arrays:
            return feed
        import jax
        try:
            return {k: jax.device_put(v) for k, v in feed.items()}
        except Exception:
            return feed

    def __call__(self):
        enforce(self._creator is not None,
                "PyReader not decorated with a generator")
        q: "queue.Queue" = queue.Queue(maxsize=self.capacity)
        err: List[BaseException] = []
        stop = threading.Event()

        def _pump():
            try:
                for item in self._creator():
                    # transfer happens on this thread → overlaps with
                    # the consumer's compute
                    if not _bounded_put(q, stop, self._device_put(
                            self._to_feed_dict(item))):
                        return  # consumer abandoned iteration
            except BaseException as e:  # surface in consumer
                err.append(e)
            finally:
                _bounded_put(q, stop, _SENTINEL)

        t = threading.Thread(target=_pump, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # break-out / GeneratorExit: unblock and retire the pump so
            # it doesn't pin `capacity` device batches forever
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    # start/reset are no-ops in iterable mode (kept for API parity)
    def start(self):
        pass

    def reset(self):
        pass


class DataLoader:
    """fluid.io.DataLoader-style factory (reference reader.py ~1.6)."""

    @staticmethod
    def from_generator(feed_list, capacity=2, iterable=True,
                       return_list=False):
        enforce(iterable, "only iterable DataLoader is supported — "
                "reader-op mode is a CUDA-interpreter concept")
        enforce(not return_list, "return_list=True is not supported: "
                "this loader yields feed dicts keyed by var name")
        return PyReader(feed_list=feed_list, capacity=capacity)
