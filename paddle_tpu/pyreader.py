"""PyReader / DataLoader: decoupled async host→device feeding
(reference: python/paddle/fluid/reader.py PyReader:45 — python
generators pump a C++ LoDTensorBlockingQueue consumed by reader ops;
buffered_reader double-buffers to device).

TPU-native shape: a background thread runs the user generator and
*pre-transfers* each batch to device (jax.device_put) while the current
step computes — the double-buffer-to-device pattern of the reference's
buffered_reader (operators/reader/buffered_reader.cc) without reader
ops, since the executor takes feeds directly."""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional, Sequence

from .core.enforce import enforce
from .data_feeder import DataFeeder

__all__ = ["PyReader", "DataLoader"]

_SENTINEL = object()


class PyReader:
    """Iterable reader bound to a list of feed Variables.

    Usage (iterable mode, the post-1.6 idiom):
        reader = PyReader(feed_list=[img, label], capacity=4)
        reader.decorate_sample_list_generator(batched_creator)
        for data in reader():          # data is a feed dict
            exe.run(main, feed=data, fetch_list=[...])
    """

    def __init__(self, feed_list: Sequence, capacity: int = 2,
                 return_device_arrays: bool = True):
        enforce(capacity >= 1, "capacity must be >= 1")
        self.feed_list = list(feed_list)
        self.capacity = capacity
        self.return_device_arrays = return_device_arrays
        self._feeder = DataFeeder(self.feed_list)
        self._creator: Optional[Callable] = None
        self._mode = None

    # -- decorators (reference reader.py:45 API surface) -------------------
    def decorate_sample_list_generator(self, creator):
        """creator() yields lists of row-tuples (one list = one batch)."""
        self._creator = creator
        self._mode = "sample_list"
        return self

    def decorate_batch_generator(self, creator):
        """creator() yields ready feed dicts or tuples of arrays."""
        self._creator = creator
        self._mode = "batch"
        return self

    def decorate_paddle_reader(self, creator):  # fluid-compat alias
        return self.decorate_sample_list_generator(creator)

    # -- iteration ---------------------------------------------------------
    def _to_feed_dict(self, item):
        if self._mode == "sample_list":
            return self._feeder.feed(item)
        if isinstance(item, dict):
            return item
        enforce(isinstance(item, (list, tuple)) and
                len(item) == len(self.feed_list),
                "batch generator must yield dicts or one array per "
                "feed var")
        return {v.name: a for v, a in zip(self.feed_list, item)}

    def _device_put(self, feed):
        if not self.return_device_arrays:
            return feed
        import jax
        try:
            return {k: jax.device_put(v) for k, v in feed.items()}
        except Exception:
            return feed

    def __call__(self):
        enforce(self._creator is not None,
                "PyReader not decorated with a generator")
        q: "queue.Queue" = queue.Queue(maxsize=self.capacity)
        err: List[BaseException] = []
        stop = threading.Event()

        def _put(item) -> bool:
            """put that aborts when the consumer went away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _pump():
            try:
                for item in self._creator():
                    # transfer happens on this thread → overlaps with
                    # the consumer's compute
                    if not _put(self._device_put(
                            self._to_feed_dict(item))):
                        return  # consumer abandoned iteration
            except BaseException as e:  # surface in consumer
                err.append(e)
            finally:
                _put(_SENTINEL)

        t = threading.Thread(target=_pump, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            # break-out / GeneratorExit: unblock and retire the pump so
            # it doesn't pin `capacity` device batches forever
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    # start/reset are no-ops in iterable mode (kept for API parity)
    def start(self):
        pass

    def reset(self):
        pass


class DataLoader:
    """fluid.io.DataLoader-style factory (reference reader.py ~1.6)."""

    @staticmethod
    def from_generator(feed_list, capacity=2, iterable=True,
                       return_list=False):
        enforce(iterable, "only iterable DataLoader is supported — "
                "reader-op mode is a CUDA-interpreter concept")
        enforce(not return_list, "return_list=True is not supported: "
                "this loader yields feed dicts keyed by var name")
        return PyReader(feed_list=feed_list, capacity=capacity)
