"""Deprecated evaluator facade.

Reference: python/paddle/fluid/evaluator.py:26-430 — already
deprecated THERE ("better to use fluid.metrics", its own warning), but
1.x model code still imports ChunkEvaluator / EditDistance /
DetectionMAP from fluid.evaluator. Each shim warns once and delegates
to the maintained implementation: the in-graph ops live in
layers.chunk_eval / layers.edit_distance / layers.detection,
host-side accumulation in metrics.py.
"""

from __future__ import annotations

import warnings

from . import metrics as _metrics

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _warn(name, use):
    warnings.warn(
        "fluid.evaluator.%s is deprecated (as in the reference); use "
        "%s instead" % (name, use), DeprecationWarning, stacklevel=3)


class ChunkEvaluator(_metrics.ChunkEvaluator):
    def __init__(self, *args, **kwargs):
        _warn("ChunkEvaluator",
              "fluid.metrics.ChunkEvaluator with layers.chunk_eval")
        super().__init__()
        # graph-building arguments of the old API are not needed:
        # feed layers.chunk_eval's counters into update()
        self._legacy_args = (args, kwargs)


class EditDistance(_metrics.EditDistance):
    def __init__(self, *args, **kwargs):
        _warn("EditDistance",
              "fluid.metrics.EditDistance with layers.edit_distance")
        super().__init__()
        self._legacy_args = (args, kwargs)


class DetectionMAP(_metrics.DetectionMAP):
    def __init__(self, *args, **kwargs):
        _warn("DetectionMAP", "fluid.metrics.DetectionMAP")
        super().__init__()
        self._legacy_args = (args, kwargs)
