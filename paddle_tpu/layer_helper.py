"""LayerHelper: parameter creation + op appending for layers.

Reference: python/paddle/fluid/layer_helper.py:29 — creates parameters in
both the startup program (with their init ops) and the main program, and
appends the layer's compute ops to the main program.
"""

from __future__ import annotations

from . import framework, unique_name
from .core.enforce import enforce
from .framework import (Parameter, Variable, default_main_program,
                        default_startup_program)
from .initializer import (ConstantInitializer, XavierInitializer,
                          _global_bias_initializer,
                          _global_weight_initializer)
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        if name is None:
            self.name = unique_name.generate(layer_type)
        else:
            self.name = name

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def append_op(self, *args, **kwargs):
        return self.block.append_op(*args, **kwargs)

    # -- variable creation -------------------------------------------------
    def create_variable_for_type_inference(self, dtype, stop_gradient=False,
                                           shape=None):
        return self.block.create_var(
            name=unique_name.generate(self.name + ".tmp"),
            dtype=dtype, shape=shape or (), stop_gradient=stop_gradient)

    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, shape, dtype, persistable=False,
                               name=None, stop_gradient=True):
        return self.main_program.global_block().create_var(
            name=name or unique_name.generate(self.name + ".global"),
            shape=shape, dtype=dtype, persistable=persistable,
            stop_gradient=stop_gradient)

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None, stop_gradient=False):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w"])) \
                if not is_bias else unique_name.generate(
                    ".".join([self.name, "b"]))
        init = attr.initializer or default_initializer
        if init is None:
            init = _global_bias_initializer() if is_bias \
                else _global_weight_initializer()

        # Shared parameters (same ParamAttr name across layers — weight
        # tying) are created ONCE: a repeated name returns the existing
        # param and appends no second init op (fluid semantics,
        # framework.py create_parameter + unique startup init).
        existing = self.main_program.global_block()._find_var_recursive(
            attr.name)
        if existing is not None:
            from .core.enforce import enforce
            enforce(tuple(existing.shape) == tuple(shape),
                    "shared parameter %r re-created with shape %s != %s"
                    % (attr.name, tuple(shape), tuple(existing.shape)))
            return existing
        # main-program parameter (metadata)
        param = self.block.create_parameter(
            name=attr.name, shape=shape, dtype=dtype,
            **{k: v for k, v in attr._to_kwargs().items() if k != "name"})
        # startup-program twin + its init op
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            name=attr.name, shape=shape, dtype=dtype,
            **{k: v for k, v in attr._to_kwargs().items() if k != "name"})
        init(sp, startup_block)
        return param

    # -- activation sugar --------------------------------------------------
    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"name": act}
        act_type = act.pop("name")
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [out]}, attrs=act)
        return out

    def append_bias_op(self, input_var, bias, axis=1):
        if bias is None:
            return input_var
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [bias]},
                       outputs={"Out": [out]}, attrs={"axis": axis})
        return out
