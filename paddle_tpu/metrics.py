"""Host-side metric accumulators (reference:
python/paddle/fluid/metrics.py — MetricBase:44, CompositeMetric:173,
Precision:231, Recall:287, Accuracy:337, ChunkEvaluator:398, EditDistance,
Auc:581, DetectionMAP).

These accumulate *numpy fetch results* across minibatches on the host —
complementary to the in-graph metric ops (layers.accuracy/auc) which run
on-device inside the step program."""

from __future__ import annotations

import numpy as np

from .core.enforce import enforce

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = str(name) if name is not None else \
            self.__class__.__name__

    @property
    def name(self):
        return self._name

    def reset(self):
        """Zero every accumulator state (reference metrics.py:86 resets
        attrs whose names start without underscore conventions)."""
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    """Evaluate several metrics over the same fetches (reference
    :173)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        enforce(isinstance(metric, MetricBase),
                "add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision = tp / (tp + fp) (reference :231)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    """Binary recall = tp / (tp + fn) (reference :287)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracies (reference
    :337)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        enforce(weight >= 0, "weight must be non-negative")
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        enforce(self.weight > 0, "no updates — call update() first")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunking F1 from per-batch (num_infer, num_label, num_correct)
    counts (reference :398)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Running mean edit distance + instance error rate (reference
    :506)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances, dtype=np.float64).ravel()
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        enforce(self.seq_num > 0, "no updates — call update() first")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """Histogram-bucketed ROC AUC over accumulated predictions
    (reference :581 — same 4096-bucket scheme as auc_op.cc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, np.int64)
        self._stat_neg = np.zeros(n, np.int64)

    def update(self, preds, labels):
        """preds: [N, 2] softmax probs or [N] / [N,1] positive-class
        probs; labels: [N(,1)] 0/1."""
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        idx = np.minimum((pos_prob * self._num_thresholds).astype(int),
                         self._num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels == 0], 1)

    def eval(self):
        # integrate TPR/FPR over buckets from highest threshold down
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.5
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapz(tpr, fpr))


class DetectionMAP(MetricBase):
    """VOC-style mean average precision over accumulated detections
    (reference: python metrics.py DetectionMAP over
    operators/detection/detection_map_op.cc). Host-side: detections
    arrive per image as [M, 6] rows (label, score, x1, y1, x2, y2) with
    ground truth [G, 4] boxes + [G] labels; matching is greedy by score
    at ``overlap_threshold`` IoU, AP integrates the PR curve
    (``ap_version``: "integral" or "11point")."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__(name)
        self.overlap_threshold = float(overlap_threshold)
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._scored = {}   # class -> list of (score, is_tp)
        self._n_gt = {}     # class -> ground-truth count

    @staticmethod
    def _iou(box, boxes):
        x1 = np.maximum(box[0], boxes[:, 0])
        y1 = np.maximum(box[1], boxes[:, 1])
        x2 = np.minimum(box[2], boxes[:, 2])
        y2 = np.minimum(box[3], boxes[:, 3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        a = (box[2] - box[0]) * (box[3] - box[1])
        b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        return inter / np.maximum(a + b - inter, 1e-10)

    def update(self, detections, gt_boxes, gt_labels, difficult=None):
        """One image's detections + ground truth."""
        detections = np.asarray(detections, np.float32).reshape(-1, 6)
        gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gt_labels = np.asarray(gt_labels).reshape(-1).astype(int)
        if difficult is None:
            difficult = np.zeros(len(gt_labels), bool)
        else:
            difficult = np.asarray(difficult).reshape(-1).astype(bool)
        for c in np.unique(gt_labels):
            count = int(np.sum((gt_labels == c) &
                               (self.evaluate_difficult |
                                ~difficult)))
            self._n_gt[int(c)] = self._n_gt.get(int(c), 0) + count
        for c in np.unique(detections[:, 0].astype(int)):
            dets = detections[detections[:, 0].astype(int) == c]
            dets = dets[np.argsort(-dets[:, 1])]
            gmask = gt_labels == c
            gboxes = gt_boxes[gmask]
            gdiff = difficult[gmask]
            taken = np.zeros(len(gboxes), bool)
            rec = self._scored.setdefault(int(c), [])
            for d in dets:
                if len(gboxes) == 0:
                    rec.append((float(d[1]), False))
                    continue
                ious = self._iou(d[2:6], gboxes)
                j = int(np.argmax(ious))
                if ious[j] >= self.overlap_threshold:
                    if not self.evaluate_difficult and gdiff[j]:
                        # matches a difficult gt: IGNORED entirely
                        # (VOC semantics — neither TP nor FP, and the
                        # difficult gt is never consumed)
                        continue
                    if not taken[j]:
                        taken[j] = True
                        rec.append((float(d[1]), True))
                    else:  # duplicate on a taken gt: FP
                        rec.append((float(d[1]), False))
                else:
                    rec.append((float(d[1]), False))

    def _ap(self, scored, n_gt):
        if n_gt == 0:
            return None  # nothing to find: class doesn't count
        if not scored:
            return 0.0   # GT present, nothing detected: AP is zero
        scored = sorted(scored, key=lambda t: -t[0])
        tp = np.cumsum([1.0 if hit else 0.0 for _, hit in scored])
        fp = np.cumsum([0.0 if hit else 1.0 for _, hit in scored])
        recall = tp / n_gt
        precision = tp / np.maximum(tp + fp, 1e-10)
        if self.ap_version == "11point":
            return float(np.mean([
                np.max(precision[recall >= r], initial=0.0)
                for r in np.linspace(0, 1, 11)]))
        # integral AP: sum precision deltas at each new recall point
        ap, prev_r = 0.0, 0.0
        for p, r in zip(precision, recall):
            ap += p * (r - prev_r)
            prev_r = r
        return float(ap)

    def eval(self):
        aps = [self._ap(self._scored.get(c, []), n)
               for c, n in self._n_gt.items()]
        aps = [a for a in aps if a is not None]
        return float(np.mean(aps)) if aps else 0.0
