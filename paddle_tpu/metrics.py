"""Host-side metric accumulators (reference:
python/paddle/fluid/metrics.py — MetricBase:44, CompositeMetric:173,
Precision:231, Recall:287, Accuracy:337, ChunkEvaluator:398, EditDistance,
Auc:581, DetectionMAP).

These accumulate *numpy fetch results* across minibatches on the host —
complementary to the in-graph metric ops (layers.accuracy/auc) which run
on-device inside the step program."""

from __future__ import annotations

import numpy as np

from .core.enforce import enforce

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = str(name) if name is not None else \
            self.__class__.__name__

    @property
    def name(self):
        return self._name

    def reset(self):
        """Zero every accumulator state (reference metrics.py:86 resets
        attrs whose names start without underscore conventions)."""
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    """Evaluate several metrics over the same fetches (reference
    :173)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        enforce(isinstance(metric, MetricBase),
                "add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision = tp / (tp + fp) (reference :231)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    """Binary recall = tp / (tp + fn) (reference :287)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracies (reference
    :337)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        enforce(weight >= 0, "weight must be non-negative")
        self.value += float(value) * float(weight)
        self.weight += float(weight)

    def eval(self):
        enforce(self.weight > 0, "no updates — call update() first")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunking F1 from per-batch (num_infer, num_label, num_correct)
    counts (reference :398)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(num_infer_chunks)
        self.num_label_chunks += int(num_label_chunks)
        self.num_correct_chunks += int(num_correct_chunks)

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Running mean edit distance + instance error rate (reference
    :506)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances, dtype=np.float64).ravel()
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        enforce(self.seq_num > 0, "no updates — call update() first")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """Histogram-bucketed ROC AUC over accumulated predictions
    (reference :581 — same 4096-bucket scheme as auc_op.cc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, np.int64)
        self._stat_neg = np.zeros(n, np.int64)

    def update(self, preds, labels):
        """preds: [N, 2] softmax probs or [N] / [N,1] positive-class
        probs; labels: [N(,1)] 0/1."""
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        idx = np.minimum((pos_prob * self._num_thresholds).astype(int),
                         self._num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels == 0], 1)

    def eval(self):
        # integrate TPR/FPR over buckets from highest threshold down
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.5
        pos = self._stat_pos[::-1].cumsum()
        neg = self._stat_neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapz(tpr, fpr))
