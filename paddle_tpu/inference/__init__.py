"""Inference deployment API: AnalysisConfig + AnalysisPredictor.

Reference: paddle/fluid/inference/ (~28k LoC) —
- `AnalysisConfig` (api/paddle_analysis_config.h): model path, device,
  optimization switches.
- `AnalysisPredictor` (api/analysis_predictor.h:46): loads the model,
  runs `OptimizeInferenceProgram` (:436 — the analysis ir-pass manager
  over the graph), then serves `Run` (:196) on a private scope.
- `CreatePaddlePredictor` factory (paddle_api.h).

TPU-native redesign: the reference's 40+ subgraph-engine passes
(TensorRT/anakin/ngraph op converters) ARE the XLA compile here — the
whole pruned program compiles to one device executable, cached per
batch shape. What remains of the analysis phase is real program-level
optimization through the ir pass framework (conv+BN fold into trained
weights, fc fusion) plus the quant freeze from contrib.slim, all
sharing the Pass/PatternDetector infrastructure (ir/).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import io as _io
from ..core.enforce import InvalidArgumentError, enforce
from ..core.scope import Scope
from ..executor import Executor

__all__ = ["AnalysisConfig", "AnalysisPredictor",
           "create_paddle_predictor", "PaddleTensor"]


class AnalysisConfig:
    """Reference: api/paddle_analysis_config.h."""

    def __init__(self, model_dir: str = None,
                 prog_file: str = None, params_file: str = None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._ir_optim = True
        self._memory_optim = True   # XLA-owned; parity switch
        self._use_tpu = True
        # ordered: conv_bn leaves conv+add, which conv_elementwise_add
        # then folds. fc_fuse runs before fc_lstm but cannot capture
        # the lstm input projection (it requires a bias add; the lstm
        # builder emits a bias-free mul, which fc_lstm matches
        # directly)
        self._passes = ["conv_bn_fuse_pass",
                        "conv_elementwise_add_fuse_pass",
                        "fc_fuse_pass", "fc_lstm_fuse_pass",
                        "seqpool_concat_fuse_pass",
                        "transpose_flatten_concat_fuse_pass",
                        "fuse_elewise_add_act_pass"]
        self._profile = False

    # -- switches (reference naming) ---------------------------------------
    def switch_ir_optim(self, on=True):
        self._ir_optim = bool(on)
        return self

    def enable_memory_optim(self, on=True):
        self._memory_optim = bool(on)
        return self

    def disable_gpu(self):
        self._use_tpu = False
        return self

    def enable_profile(self):
        self._profile = True
        return self

    def pass_builder(self) -> List[str]:
        """Mutable pass list (reference: paddle_pass_builder.h)."""
        return self._passes

    def delete_pass(self, name):
        self._passes = [p for p in self._passes if p != name]
        return self


class PaddleTensor:
    """Input/output container (reference: paddle_api.h PaddleTensor —
    name + shape + data). Accepts/yields numpy."""

    def __init__(self, data, name=""):
        self.data = np.asarray(data)
        self.name = name

    @property
    def shape(self):
        return tuple(self.data.shape)


class AnalysisPredictor:
    """Reference: api/analysis_predictor.h:46. Thread-safe for
    concurrent ``predict``: clones share the loaded program, the weight
    scope, AND one Executor (so every clone hits the same per-shape
    compiled-executable cache); the first compile of each feed shape is
    guarded by a per-shape gate so two threads racing the same shape
    bucket can never compile the same executable twice."""

    def __init__(self, config: AnalysisConfig):
        enforce(config.model_dir,
                "AnalysisConfig needs a model_dir (save_inference_model "
                "output)")
        self.config = config
        self.scope = Scope()
        self.exe = Executor()
        self.program, self.feed_names, self.fetch_vars = \
            _io.load_inference_model(
                config.model_dir, self.exe,
                model_filename=config.prog_file,
                params_filename=config.params_file, scope=self.scope)
        if config._ir_optim:
            self._optimize_program()
        self._init_compile_guard()

    @classmethod
    def from_program(cls, program, feed_names, fetch_vars, scope,
                     config: Optional[AnalysisConfig] = None,
                     ir_optim: bool = False) -> "AnalysisPredictor":
        """Build a predictor around an ALREADY-LOADED inference program
        + scope (no disk round-trip) — the path contrib.Inferencer and
        in-process serving use. ``ir_optim`` defaults off: the caller
        owns the program and may not want its weights rewritten by the
        fusion passes."""
        p = cls.__new__(cls)
        p.config = config or AnalysisConfig()
        p.scope = scope
        p.exe = Executor()
        p.program = program
        p.feed_names = list(feed_names)
        p.fetch_vars = list(fetch_vars)
        if ir_optim:
            p._optimize_program()
        p._init_compile_guard()
        return p

    def _init_compile_guard(self):
        # shared (by reference) with every clone: the compiled-shape
        # set, the per-shape gates, and the lock that creates gates
        self._compiled_shapes = set()
        self._shape_gates = {}
        self._gate_lock = threading.Lock()
        # model-parallel serving (enable_mesh): None = plain
        self._dist_program = None
        self._mesh_axes = None

    def enable_mesh(self, axes: Dict[str, int]) -> "AnalysisPredictor":
        """Serve this model as ONE pjit'd forward over a device mesh —
        the sharded-group-inference executor path (docs/parallel.md):
        a model bigger than one replica's HBM runs with its weights
        partitioned over ``tp`` (every ≥2-D parameter shards its
        largest divisible dim; GSPMD inserts the ICI collectives) and
        its attention sequence-sharded over ``sp`` (the
        zigzag/Ulysses routing the compiler does for training). Axis
        sizes must multiply to the local device count — on a TPU
        replica group each member host contributes its slice of the
        same mesh via jax.distributed; the CPU probe emulates the
        group's mesh with virtual host devices.

        Returns self. Clones share the distributed program (weights
        stay sharded once placed)."""
        import jax
        import numpy as _np

        from ..compiler import CompiledProgram
        from ..parallel import mesh as mesh_lib
        from ..parallel.api import shard as _shard
        ndev = int(_np.prod(list(axes.values()))) if axes else 1
        mesh = mesh_lib.make_mesh(dict(axes), jax.devices()[:ndev])
        tp = int(axes.get("tp", 1))
        if tp > 1:
            for p in self.program.all_parameters():
                if p.sharding is not None or len(p.shape) < 2:
                    continue
                # shard the LAST divisible dim (output features for
                # fc weights — column-parallel, the Megatron default);
                # semantics stay global either way, GSPMD closes the
                # seams
                for dim in range(len(p.shape) - 1, -1, -1):
                    if p.shape[dim] and p.shape[dim] % tp == 0:
                        spec = [None] * len(p.shape)
                        spec[dim] = "tp"
                        _shard(p, *spec)
                        break
        self._mesh_axes = dict(axes)
        self._dist_program = CompiledProgram(self.program) \
            .with_data_parallel(mesh=mesh)
        return self

    def _optimize_program(self):
        """OptimizeInferenceProgram (analysis_predictor.cc:436): run
        the analysis passes over the loaded program — with the scope,
        because conv_bn folding rewrites trained weights."""
        from .. import ir
        ir.apply_passes(self.program, self.config._passes,
                        scope=self.scope)

    @property
    def signature(self) -> dict:
        """Model I/O signature (names, dtypes, static/dynamic dims).
        Prefers the ``__signature__.json`` sidecar written by
        save_inference_model; models saved before the sidecar existed
        derive the same dict live from the program declaration."""
        sig = getattr(self.program, "_inference_signature", None)
        if sig is None:
            sig = _io.infer_signature(self.program, self.feed_names,
                                      self.fetch_vars)
        return sig

    # -- serving ------------------------------------------------------------
    def _run_feed(self, feed: Dict[str, np.ndarray], return_numpy=True):
        """One executor run with the first-compile of each feed-shape
        signature serialized behind a per-shape gate. The steady state
        (shape already compiled) takes no lock at all; only the two
        threads racing an UNSEEN shape serialize, and the loser finds
        the executable cached instead of compiling its own. ``donate``
        is off: concurrent runs share the weight scope, and donation
        would invalidate param buffers a sibling thread still reads."""
        fetch = [v.name for v in self.fetch_vars]
        prog = self._dist_program if self._dist_program is not None \
            else self.program

        def run():
            return self.exe.run(prog, feed=feed,
                                fetch_list=fetch, scope=self.scope,
                                return_numpy=return_numpy,
                                donate=False)

        key = tuple(sorted((k, tuple(np.shape(v)))
                           for k, v in feed.items()))
        if key not in self._compiled_shapes:
            with self._gate_lock:
                gate = self._shape_gates.setdefault(key,
                                                    threading.Lock())
            with gate:
                if key not in self._compiled_shapes:
                    outs = run()
                    self._compiled_shapes.add(key)
                    return outs
        return run()

    def run(self, inputs: Sequence) -> List[PaddleTensor]:
        """Positional inputs in feed_names order (reference
        AnalysisPredictor::Run, analysis_predictor.cc:196)."""
        enforce(len(inputs) == len(self.feed_names),
                "model expects %d inputs (%s), got %d"
                % (len(self.feed_names), self.feed_names, len(inputs)))
        feed = {}
        for name, t in zip(self.feed_names, inputs):
            feed[name] = t.data if isinstance(t, PaddleTensor) \
                else np.asarray(t)
        outs = self._run_feed(feed)
        return [PaddleTensor(o, v.name)
                for o, v in zip(outs, self.fetch_vars)]

    def predict(self, feed: Dict[str, np.ndarray],
                return_numpy=True) -> List[np.ndarray]:
        """Dict-feed convenience (not in the reference C API)."""
        return list(self._run_feed(feed, return_numpy=return_numpy))

    def clone(self) -> "AnalysisPredictor":
        """Per-thread clone SHARING the loaded program, the weight
        scope, and the Executor (reference: analysis_predictor.cc
        Clone shares the program; weights are read-only at inference)
        — no disk reload, no re-run of the ir passes, and one
        per-shape compiled-executable cache across all clones. The
        shared compile guard makes concurrent first-compiles of the
        same shape happen exactly once."""
        c = AnalysisPredictor.__new__(AnalysisPredictor)
        c.config = self.config
        c.scope = self.scope
        c.exe = self.exe
        c.program = self.program
        c.feed_names = list(self.feed_names)
        c.fetch_vars = list(self.fetch_vars)
        c._compiled_shapes = self._compiled_shapes
        c._shape_gates = self._shape_gates
        c._gate_lock = self._gate_lock
        c._dist_program = self._dist_program
        c._mesh_axes = self._mesh_axes
        return c

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return [v.name for v in self.fetch_vars]


def create_paddle_predictor(config: AnalysisConfig) -> AnalysisPredictor:
    """Reference: CreatePaddlePredictor<AnalysisConfig> (paddle_api.h)."""
    return AnalysisPredictor(config)
