"""Training-curve plotting helper used throughout the book tutorials.

Reference: python/paddle/utils/plot.py:17-116 (PlotData/Ploter —
matplotlib when a display exists, silent data collection otherwise).
Headless TPU pods are the common case here, so the data always
accumulates and drawing is best-effort."""

from __future__ import annotations

import os


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    """Ploter("train cost", "test cost"); .append(title, step, value);
    .plot(path=None) draws (or saves) one figure with all series."""

    def __init__(self, *titles):
        self.__args__ = titles
        self.__plot_data__ = {t: PlotData() for t in titles}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "")

    def __plot_is_disabled__(self):
        return self.__disable_plot__.lower() == "true"

    def append(self, title, step, value):
        if title not in self.__plot_data__:
            raise KeyError(
                "no such series %r (declared: %s)"
                % (title, list(self.__plot_data__)))
        self.__plot_data__[title].append(step, value)

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        try:
            import matplotlib
            if path is not None or not os.environ.get("DISPLAY"):
                matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except Exception:
            return  # headless image without matplotlib: keep the data
        plt.figure()
        for title, data in self.__plot_data__.items():
            plt.plot(data.step, data.value, label=title)
        plt.legend()
        if path is not None:
            plt.savefig(path)
        else:
            plt.show()
        plt.close()
