"""paddle.utils analog (reference: python/paddle/utils/__init__.py).
The v1-era preprocess_img/torch2paddle legacy helpers are not ported
(dead surface per SURVEY); plot.Ploter is, because every book chapter
draws its cost curve with it."""
from . import plot  # noqa: F401
from .plot import Ploter  # noqa: F401
