"""Profiler: host-side event spans + aggregate tables + chrome trace.

Reference: paddle/fluid/platform/profiler.{h,cc} (RAII ``RecordEvent``
profiler.h:81, ``EnableProfiler/DisableProfiler`` :166-171 aggregating
min/max/avg tables from profiler.proto), platform/device_tracer.cc
(CUPTI device activity), python/paddle/fluid/profiler.py:39-222
(profiler/start_profiler/stop_profiler/reset_profiler/cuda_profiler)
and tools/timeline.py (proto -> chrome://tracing JSON).

TPU-native redesign: there is no per-op runtime to instrument — the
whole step is ONE fused XLA program — so host events cover the step
pipeline (trace/compile/run/fetch, recorded by the Executor) and any
user spans, while *device*-side detail comes from the XLA profiler
(``jax.profiler``, the CUPTI/DeviceTracer analog): pass
``profile_path`` and a TensorBoard/xprof trace is captured alongside.
Chrome-trace export works directly from the host events (the
timeline.py role)."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["RecordEvent", "record_event", "start_profiler",
           "stop_profiler", "reset_profiler", "reset_counters",
           "profiler", "export_chrome_tracing",
           "device_summary_table", "bump_counter", "counter_values",
           "cuda_profiler", "npu_profiler"]

_state = threading.local()
_lock = threading.Lock()
_enabled = False
_events: List["_Event"] = []
_device_trace_dir: Optional[str] = None
# host perf_counter captured immediately before jax start_trace: the
# xplane timebase starts there, so host and device events share one
# timeline (skew is the start_trace call latency, sub-ms)
_trace_anchor: Optional[float] = None
_device_events: List[dict] = []


@dataclass
class _Event:
    name: str
    start: float
    end: float
    thread: int
    depth: int
    args: Optional[dict] = None

    @property
    def dur(self):
        return self.end - self.start


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class RecordEvent:
    """RAII span (reference: platform/profiler.h:81). Usable as a
    context manager or via ``record_event``. No-op unless the profiler
    is enabled — cheap enough to leave in hot paths. ``args`` (a small
    JSON-able dict, e.g. the serving engine's batch bucket/occupancy)
    rides into the chrome-trace span's args panel."""

    def __init__(self, name, args=None):
        self.name = name
        self.args = args
        self._t0 = None

    def __enter__(self):
        if _enabled:
            self._t0 = time.perf_counter()
            _stack().append(self.name)
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            end = time.perf_counter()
            stack = _stack()
            depth = len(stack) - 1
            stack.pop()
            ev = _Event(name=self.name, start=self._t0, end=end,
                        thread=threading.get_ident(), depth=depth,
                        args=self.args)
            with _lock:
                _events.append(ev)
        return False


record_event = RecordEvent


# -- always-on scalar counters ---------------------------------------
# Unlike spans, counters accumulate regardless of start_profiler: the
# input-pipeline stall metric (time the device dispatch loop waited on
# host data) must be measurable from a plain bench/probe run without
# turning on the full event recorder. Cost per bump is one lock + one
# float add. Storage is the process-wide observability.MetricsRegistry
# (same hot-path cost), so these counters show up in /metrics and
# obs_dump next to every other subsystem's.
_bump_names: set = set()


def _registry():
    from .observability import registry
    return registry()


def bump_counter(name, value=1.0):
    _bump_names.add(name)  # set.add is atomic under the GIL
    _registry().counter(name).inc(value)


def counter_values() -> dict:
    reg = _registry()
    return {n: reg.counter(n).value for n in sorted(_bump_names)}


def reset_counters():
    """Zero the always-on counters. Deliberately SEPARATE from
    ``reset_profiler``: counters back stall accounting and bench
    probes that must survive span resets — a probe that clears spans
    between phases must not silently lose its stall tally."""
    reg = _registry()
    for n in list(_bump_names):
        reg.counter(n).reset()


def start_profiler(state="All", trace_path=None):
    """Reference: profiler.py start_profiler (state CPU/GPU/All; GPU
    maps to the TPU/XLA device trace here). ``trace_path`` starts a
    jax.profiler trace capturing device activity (xprof)."""
    global _enabled, _device_trace_dir
    if _enabled:
        return
    _enabled = True
    if trace_path and state in ("GPU", "TPU", "All"):
        global _trace_anchor
        try:
            import jax
            _trace_anchor = time.perf_counter()
            jax.profiler.start_trace(trace_path)
            _device_trace_dir = trace_path
        except Exception:
            _device_trace_dir = None
            _trace_anchor = None


def reset_profiler():
    """Clear recorded SPANS (host + device events) only. The always-on
    counters are NOT touched — ``pyreader`` stall accounting and bench
    probes depend on them accumulating across span resets; clear those
    explicitly with ``reset_counters()``."""
    with _lock:
        _events.clear()
        _device_events.clear()


def stop_profiler(sorted_key=None, profile_path=None):
    """Aggregate + print the event table (reference: DisableProfiler →
    PrintProfiler, profiler.cc); optionally dump chrome tracing JSON to
    ``profile_path`` (the timeline.py step, no separate tool needed)."""
    global _enabled, _device_trace_dir
    if not _enabled:
        return
    _enabled = False
    if _device_trace_dir is not None:
        try:
            import jax
            jax.profiler.stop_trace()
            _collect_device_events(_device_trace_dir)
        except Exception:
            pass
        _device_trace_dir = None
    if profile_path:
        export_chrome_tracing(profile_path)
    print(summary_table(sorted_key))
    if _device_events:
        print(device_summary_table())


def summary_table(sorted_key=None) -> str:
    with _lock:
        events = list(_events)
    agg = {}
    for ev in events:
        rec = agg.setdefault(ev.name,
                             {"calls": 0, "total": 0.0,
                              "min": float("inf"), "max": 0.0})
        rec["calls"] += 1
        rec["total"] += ev.dur
        rec["min"] = min(rec["min"], ev.dur)
        rec["max"] = max(rec["max"], ev.dur)
    wall = sum(r["total"] for r in agg.values()) or 1.0
    rows = []
    for name, r in agg.items():
        rows.append((name, r["calls"], r["total"] * 1e3,
                     r["min"] * 1e3, r["max"] * 1e3,
                     r["total"] / r["calls"] * 1e3,
                     r["total"] / wall))
    key = {None: lambda x: -x[2], "default": lambda x: -x[2],
           "total": lambda x: -x[2], "calls": lambda x: -x[1],
           "name": lambda x: x[0], "max": lambda x: -x[4],
           "min": lambda x: -x[3], "ave": lambda x: -x[5]}[sorted_key]
    rows.sort(key=key)
    lines = ["------------------------->     Profiling Report     "
             "<-------------------------", "",
             "%-32s %8s %12s %10s %10s %10s %8s" %
             ("Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)",
              "Ave(ms)", "Ratio")]
    for name, calls, total, mn, mx, ave, ratio in rows:
        lines.append("%-32s %8d %12.4f %10.4f %10.4f %10.4f %7.2f%%"
                     % (name[:32], calls, total, mn, mx, ave,
                        ratio * 100.0))
    return "\n".join(lines)


def _collect_device_events(trace_dir):
    """Parse the captured xplane files into per-op device events —
    the DeviceTracer/CUPTI-activity analog (reference:
    platform/device_tracer.cc:41). Device planes ("/device:TPU:*")
    carry one line per core stream; on CPU backends the XLA runtime
    threads ("tf_*" lines of the host plane) play that role."""
    import glob
    global _device_events
    from jax.profiler import ProfileData
    events = []
    for f in glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True):
        pd = ProfileData.from_file(f)
        planes = list(pd.planes)
        dev_planes = [p for p in planes
                      if p.name.startswith("/device:")]
        if dev_planes:
            selected = [(p.name, line) for p in dev_planes
                        for line in p.lines]
        else:
            selected = [(p.name, line) for p in planes
                        if p.name.endswith(":CPU")
                        for line in p.lines
                        if line.name.startswith("tf_")]
        for pname, line in selected:
            for e in line.events:
                if e.duration_ns <= 0 or \
                        e.name.startswith(("end: ", "begin: ")):
                    continue
                events.append({"name": e.name, "plane": pname,
                               "line": line.name,
                               "ts_ns": float(e.start_ns),
                               "dur_ns": float(e.duration_ns)})
    with _lock:
        _device_events = events


def device_summary_table(sorted_key=None) -> str:
    """Per-op DEVICE time table from the xplane capture (reference:
    the 'GPU' rows of PrintProfiler + tools/timeline.py device
    tracks)."""
    with _lock:
        events = list(_device_events)
    agg = {}
    for ev in events:
        rec = agg.setdefault(ev["name"],
                             {"calls": 0, "total": 0.0,
                              "min": float("inf"), "max": 0.0})
        rec["calls"] += 1
        d = ev["dur_ns"] / 1e6
        rec["total"] += d
        rec["min"] = min(rec["min"], d)
        rec["max"] = max(rec["max"], d)
    wall = sum(r["total"] for r in agg.values()) or 1.0
    rows = [(n, r["calls"], r["total"], r["min"], r["max"],
             r["total"] / r["calls"], r["total"] / wall)
            for n, r in agg.items()]
    rows.sort(key=lambda x: -x[2])
    lines = ["------------------------->   Device (XLA) Report   "
             "<-------------------------", "",
             "%-40s %8s %12s %10s %10s %8s" %
             ("Op", "Calls", "Total(ms)", "Min(ms)", "Max(ms)",
              "Ratio")]
    for name, calls, total, mn, mx, _ave, ratio in rows[:60]:
        lines.append("%-40s %8d %12.4f %10.4f %10.4f %7.2f%%"
                     % (name[:40], calls, total, mn, mx,
                        ratio * 100.0))
    return "\n".join(lines)


def export_chrome_tracing(path):
    """ONE chrome://tracing JSON merging host RecordEvents and the
    captured device-op events on separate tracks (reference:
    tools/timeline.py merging profiler.proto host records with
    device_tracer.cc CUPTI records). Host events are aligned to the
    device timebase via the anchor captured at start_trace."""
    with _lock:
        events = list(_events)
        dev = list(_device_events)
    if _trace_anchor is not None and dev:
        base = _trace_anchor
    elif events:
        base = min(ev.start for ev in events)
    else:
        base = 0.0
    trace_events = [
        {"name": ev.name, "cat": "host", "ph": "X",
         "ts": (ev.start - base) * 1e6, "dur": ev.dur * 1e6,
         "pid": 0, "tid": ev.thread % 10000,
         "args": dict({"depth": ev.depth}, **(ev.args or {}))}
        for ev in events]
    tids = {}
    for ev in dev:
        tid = tids.setdefault((ev["plane"], ev["line"]),
                              len(tids) + 1)
        trace_events.append(
            {"name": ev["name"], "cat": "device", "ph": "X",
             "ts": ev["ts_ns"] / 1e3, "dur": ev["dur_ns"] / 1e3,
             "pid": 1, "tid": tid, "args": {"stream": ev["line"]}})
    # wall-clock anchor: trace ts is perf_counter-based (per-process
    # arbitrary epoch), so cross-process merge (tools/trace_merge.py)
    # needs a (wall_time, trace_ts) correspondence to rebase timelines
    now_wall = time.time()
    now_ts = (time.perf_counter() - base) * 1e6
    from .observability import journal as _obs_journal
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "host"}},
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": "device (XLA)"}},
            {"name": "clock_sync", "ph": "M", "pid": 0,
             "args": {"wall_time_s": now_wall, "trace_ts_us": now_ts,
                      "role": _obs_journal.get_role(),
                      "pid_os": os.getpid()}}]
    # always-on counters ride along as chrome counter samples (one
    # terminal sample per counter — totals, not a timeseries)
    for cname, cval in counter_values().items():
        trace_events.append(
            {"name": cname, "cat": "counter", "ph": "C",
             "ts": now_ts, "pid": 0, "tid": 0,
             "args": {cname: cval}})
    trace = {"traceEvents": meta + trace_events}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             trace_path=None):
    """Reference: profiler.py profiler() context manager."""
    start_profiler(state, trace_path=trace_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Accepted for API parity; device tracing on TPU goes through
    ``trace_path``/jax.profiler (reference: profiler.py cuda_profiler
    wrapping cudaProfilerStart/Stop)."""
    yield


npu_profiler = cuda_profiler
