"""Profiler: host-side event spans + aggregate tables + chrome trace.

Reference: paddle/fluid/platform/profiler.{h,cc} (RAII ``RecordEvent``
profiler.h:81, ``EnableProfiler/DisableProfiler`` :166-171 aggregating
min/max/avg tables from profiler.proto), platform/device_tracer.cc
(CUPTI device activity), python/paddle/fluid/profiler.py:39-222
(profiler/start_profiler/stop_profiler/reset_profiler/cuda_profiler)
and tools/timeline.py (proto -> chrome://tracing JSON).

TPU-native redesign: there is no per-op runtime to instrument — the
whole step is ONE fused XLA program — so host events cover the step
pipeline (trace/compile/run/fetch, recorded by the Executor) and any
user spans, while *device*-side detail comes from the XLA profiler
(``jax.profiler``, the CUPTI/DeviceTracer analog): pass
``profile_path`` and a TensorBoard/xprof trace is captured alongside.
Chrome-trace export works directly from the host events (the
timeline.py role)."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["RecordEvent", "record_event", "start_profiler",
           "stop_profiler", "reset_profiler", "profiler",
           "export_chrome_tracing", "cuda_profiler", "npu_profiler"]

_state = threading.local()
_lock = threading.Lock()
_enabled = False
_events: List["_Event"] = []
_device_trace_dir: Optional[str] = None


@dataclass
class _Event:
    name: str
    start: float
    end: float
    thread: int
    depth: int

    @property
    def dur(self):
        return self.end - self.start


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class RecordEvent:
    """RAII span (reference: platform/profiler.h:81). Usable as a
    context manager or via ``record_event``. No-op unless the profiler
    is enabled — cheap enough to leave in hot paths."""

    def __init__(self, name):
        self.name = name
        self._t0 = None

    def __enter__(self):
        if _enabled:
            self._t0 = time.perf_counter()
            _stack().append(self.name)
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            end = time.perf_counter()
            stack = _stack()
            depth = len(stack) - 1
            stack.pop()
            ev = _Event(name=self.name, start=self._t0, end=end,
                        thread=threading.get_ident(), depth=depth)
            with _lock:
                _events.append(ev)
        return False


record_event = RecordEvent


def start_profiler(state="All", trace_path=None):
    """Reference: profiler.py start_profiler (state CPU/GPU/All; GPU
    maps to the TPU/XLA device trace here). ``trace_path`` starts a
    jax.profiler trace capturing device activity (xprof)."""
    global _enabled, _device_trace_dir
    if _enabled:
        return
    _enabled = True
    if trace_path and state in ("GPU", "TPU", "All"):
        try:
            import jax
            jax.profiler.start_trace(trace_path)
            _device_trace_dir = trace_path
        except Exception:
            _device_trace_dir = None


def reset_profiler():
    with _lock:
        _events.clear()


def stop_profiler(sorted_key=None, profile_path=None):
    """Aggregate + print the event table (reference: DisableProfiler →
    PrintProfiler, profiler.cc); optionally dump chrome tracing JSON to
    ``profile_path`` (the timeline.py step, no separate tool needed)."""
    global _enabled, _device_trace_dir
    if not _enabled:
        return
    _enabled = False
    if _device_trace_dir is not None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass
        _device_trace_dir = None
    if profile_path:
        export_chrome_tracing(profile_path)
    print(summary_table(sorted_key))


def summary_table(sorted_key=None) -> str:
    with _lock:
        events = list(_events)
    agg = {}
    for ev in events:
        rec = agg.setdefault(ev.name,
                             {"calls": 0, "total": 0.0,
                              "min": float("inf"), "max": 0.0})
        rec["calls"] += 1
        rec["total"] += ev.dur
        rec["min"] = min(rec["min"], ev.dur)
        rec["max"] = max(rec["max"], ev.dur)
    wall = sum(r["total"] for r in agg.values()) or 1.0
    rows = []
    for name, r in agg.items():
        rows.append((name, r["calls"], r["total"] * 1e3,
                     r["min"] * 1e3, r["max"] * 1e3,
                     r["total"] / r["calls"] * 1e3,
                     r["total"] / wall))
    key = {None: lambda x: -x[2], "default": lambda x: -x[2],
           "total": lambda x: -x[2], "calls": lambda x: -x[1],
           "name": lambda x: x[0], "max": lambda x: -x[4],
           "min": lambda x: -x[3], "ave": lambda x: -x[5]}[sorted_key]
    rows.sort(key=key)
    lines = ["------------------------->     Profiling Report     "
             "<-------------------------", "",
             "%-32s %8s %12s %10s %10s %10s %8s" %
             ("Event", "Calls", "Total(ms)", "Min(ms)", "Max(ms)",
              "Ave(ms)", "Ratio")]
    for name, calls, total, mn, mx, ave, ratio in rows:
        lines.append("%-32s %8d %12.4f %10.4f %10.4f %10.4f %7.2f%%"
                     % (name[:32], calls, total, mn, mx, ave,
                        ratio * 100.0))
    return "\n".join(lines)


def export_chrome_tracing(path):
    """chrome://tracing JSON from the host events (reference:
    tools/timeline.py converting profiler.proto)."""
    with _lock:
        events = list(_events)
    if not events:
        base = 0.0
    else:
        base = min(ev.start for ev in events)
    trace = {"traceEvents": [
        {"name": ev.name, "cat": "host", "ph": "X",
         "ts": (ev.start - base) * 1e6, "dur": ev.dur * 1e6,
         "pid": 0, "tid": ev.thread % 10000,
         "args": {"depth": ev.depth}}
        for ev in events]}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None,
             trace_path=None):
    """Reference: profiler.py profiler() context manager."""
    start_profiler(state, trace_path=trace_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Accepted for API parity; device tracing on TPU goes through
    ``trace_path``/jax.profiler (reference: profiler.py cuda_profiler
    wrapping cudaProfilerStart/Stop)."""
    yield


npu_profiler = cuda_profiler
