"""LoD-tensor migration bridge.

Reference: python/paddle/fluid/lod_tensor.py:22-151
(create_lod_tensor / create_random_int_lodtensor). This framework has
NO LoD metadata by design (SURVEY: TPUs want static shapes — every
sequence op takes padded data + an explicit lengths vector instead),
so these helpers return the padded+lengths pair directly: the exact
feed format `layers.data([max_len, ...]) + seq_len=` sites consume.
A reference program migrates by replacing its one create_lod_tensor
call and threading the returned lengths into its sequence ops.
"""

from __future__ import annotations

import numpy as np

from .core.enforce import enforce

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Pack ragged rows into (padded [batch, max_len, ...], lengths
    [batch] int64).

    ``data``: flat ndarray of shape [sum(lens), ...] (the reference's
    LoDTensor storage layout) or a Python list of per-sequence lists.
    ``recursive_seq_lens``: one level, e.g. [[2, 3]] — deeper LoD
    nesting was only used by nested-sequence ops the padded redesign
    scopes out. ``place`` is accepted for signature parity.
    """
    del place
    enforce(recursive_seq_lens and len(recursive_seq_lens) == 1,
            "padded+lengths replaces exactly ONE LoD level; got %r "
            "levels (nested sequences: restructure as [batch, outer, "
            "inner] padded dims)"
            % (len(recursive_seq_lens or ())))
    lens = list(recursive_seq_lens[0])
    enforce(all(int(n) >= 0 for n in lens),
            "sequence lengths must be >= 0, got %r" % (lens,))
    if isinstance(data, (list, tuple)):
        flat = np.concatenate(
            [np.asarray(seq).reshape(len(seq), -1) for seq in data
             if len(seq)], axis=0) if any(len(s) for s in data) \
            else np.zeros((0, 1))
        enforce(len(data) == len(lens) or sum(lens) == sum(
            len(s) for s in data),
            "list data does not match recursive_seq_lens")
    else:
        flat = np.asarray(data)
    total = int(sum(lens))
    enforce(flat.shape[0] == total,
            "data rows (%d) != sum of sequence lengths (%d)"
            % (flat.shape[0], total))
    max_len = max(lens) if lens else 0
    padded = np.zeros((len(lens), max_len) + flat.shape[1:],
                      dtype=flat.dtype)
    off = 0
    for i, n in enumerate(lens):
        padded[i, :n] = flat[off:off + n]
        off += n
    return padded, np.asarray(lens, dtype=np.int64)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    """Reference lod_tensor.py:100 — random ints in [low, high] packed
    per ``create_lod_tensor``."""
    enforce(recursive_seq_lens and len(recursive_seq_lens) == 1,
            "one LoD level (see create_lod_tensor)")
    total = int(sum(recursive_seq_lens[0]))
    shape = (total,) + tuple(base_shape)
    data = np.random.randint(low, high + 1, size=shape).astype(np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)
