"""GuardedTrainer: the guarded training driver.

Reference analog: Fluid survives production because the RUNTIME owns
failure handling — checkpoint_notify machinery flushes parameter-server
shards on preemption (distribute_transpiler.py:1612) and the RPC layer
retries through pserver restarts. This driver composes the same three
layers for the TPU-native executor:

  in-graph   anomaly guard (guard.py): a non-finite step's update is a
             select-no-op inside the compiled step; skipped/consecutive
             counters ride the persistable carry.
  host loop  auto-rollback: after K consecutive anomalous steps the
             latest complete checkpoint (weights + optimizer moments +
             q8 error-feedback residuals — ALL persistables) is
             restored and training resumes. The PRNG stream never
             rewinds: the executor folds its base key with a
             monotonically increasing run counter, so replayed steps
             draw FRESH dropout masks instead of deterministically
             re-poisoning themselves.
  dispatch   retry/backoff (retry.py): transient PJRT failures are
             retried under a budget; exhaustion degrades gracefully to
             a final synchronous checkpoint plus a structured
             ``TrainingAborted`` report.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import observability as _obs
from ..core.enforce import enforce
from . import guard as guard_mod
from .retry import RetryBudgetExhausted, RetryPolicy, retry_call


class TrainingAborted(RuntimeError):
    """Raised when the trainer gives up (retry budget exhausted, or the
    rollback budget spent on a persistent anomaly). A final synchronous
    checkpoint has already been flushed; ``.report`` carries the
    structured training summary."""

    def __init__(self, reason: str, report: Dict):
        self.reason = reason
        self.report = report
        super().__init__("%s\nsummary: %r" % (reason, report))


class GuardedTrainer:
    """Drives ``executor.run`` / ``run_repeated`` over a program whose
    traced step carries the anomaly guard.

    Parameters
    ----------
    executor, program, loss : the usual trio; ``install_anomaly_guard``
        is applied to ``program`` here (idempotent) unless
        ``guard=False``. If ``startup_program`` is given it runs first.
    checkpoint_dir : directory for the ``io.CheckpointSaver``; required
        for rollback (``rollback_after``) to have a restore target. A
        step-0 checkpoint is flushed synchronously at the first
        ``train`` call so rollback is ALWAYS possible.
    checkpoint_every : save cadence in steps (0 = only the initial and
        final checkpoints). ``sync_saves=False`` writes in the
        background (training never blocks on the filesystem).
    rollback_after : K — consecutive anomalous steps that trigger a
        restore of the latest complete checkpoint. 0 disables rollback.
    max_rollbacks : rollback budget; a persistent anomaly that keeps
        re-triggering aborts once it is spent.
    retry : RetryPolicy for transient dispatch failures.
    faults : optional resilience.faults.FaultInjector (chaos testing).
    hang_deadline_s : health-plane stall deadline — while training, a
        device dispatch in flight with no completion for this long
        gets an unhealthy watchdog verdict (journal ``health`` event +
        blackbox dump when a dump dir is armed): the silent
        backend-hang class no retry policy can see, because the
        dispatch call never returns. Generous by default so a cold
        multi-minute XLA compile is never misread as a wedge; None
        disables. A ``TrainingAborted`` also dumps the black box.
    """

    def __init__(self, executor, program, loss, startup_program=None,
                 scope=None, checkpoint_dir=None, checkpoint_every=0,
                 max_to_keep=3, rollback_after=3, max_rollbacks=2,
                 retry: Optional[RetryPolicy] = None, faults=None,
                 guard: bool = True, sync_saves: bool = False,
                 hang_deadline_s: Optional[float] = 900.0,
                 stages=()):
        from .. import io as io_mod
        from ..core.scope import global_scope
        from ..engine import StepEngine
        self._exe = executor
        # every per-step dispatch is one engine-composed step; host
        # exchanges (the PS phase, the sparse pull/push) ride along as
        # ``stages`` — composition legality is checked ONCE here, with
        # the static matrix's exact message (engine.rules)
        self._engine = StepEngine(executor)
        self._stages = tuple(stages)
        StepEngine.check_composition(program, k=1, stages=self._stages)
        # ``program`` may be a CompiledProgram (the q8 collective path):
        # dispatch goes through it, while the guard install and the
        # checkpoint saver operate on the underlying Program
        self._program = program
        self._base_program = program.program \
            if getattr(program, "_is_compiled", False) else program
        self._loss = loss
        self._scope = scope or global_scope()
        self._guard_on = bool(guard)
        if startup_program is not None:
            executor.run(startup_program, scope=self._scope)
        if self._guard_on:
            guard_mod.install_anomaly_guard(self._base_program,
                                            loss=loss,
                                            scope=self._scope)
        if self._program is not self._base_program:
            bs = getattr(self._program, "_build_strategy", None)
            if getattr(bs, "gradient_sync", None) == "q8":
                # the q8 error-feedback residuals must exist BEFORE the
                # initial checkpoint: a rollback to ckpt-0 that lacked
                # them could not restore the block's full persistable
                # set once training had created them
                from ..parallel.collectives import ensure_residual_vars
                ensure_residual_vars(self._base_program, self._scope)
        self._saver = None
        if checkpoint_dir is not None:
            self._saver = io_mod.CheckpointSaver(
                checkpoint_dir, self._base_program,
                max_to_keep=max_to_keep, scope=self._scope)
            if faults is not None:
                faults.attach_saver(self._saver)
        self._checkpoint_every = int(checkpoint_every)
        self._rollback_after = int(rollback_after)
        self._max_rollbacks = int(max_rollbacks)
        self._retry = retry or RetryPolicy()
        self._faults = faults
        self._sync_saves = bool(sync_saves)
        self._hang_deadline_s = hang_deadline_s
        self._health_watch = None
        # -- structured summary state -----------------------------------
        self._steps_run = 0
        self._retries = 0
        self._rollbacks = 0
        self._save_failures = 0
        self._skipped_host = 0.0  # tally absorbed at rollback resets
        self._last_finite_loss = None
        self._losses: List[float] = []
        self._aborted = None
        self._initial_ckpt_done = False

    # -- public API ----------------------------------------------------
    def train(self, feeds, fetch_list=None):
        """Run one guarded pass over ``feeds``.

        ``feeds``: a SEQUENCE of feed dicts (replayable — rollback
        rewinds the cursor so the poisoned window's batches are
        replayed), or an ITERATOR (stream; rollback restores state but
        continues with the next batches, since a stream cannot be
        replayed — the train_from_dataset posture). Returns the
        summary dict.
        """
        replayable = isinstance(feeds, (list, tuple))
        if not replayable:
            feeds = iter(feeds)
        self._arm_hang_watch()
        try:
            self._ensure_initial_checkpoint()
            fetch = list(fetch_list) if fetch_list else [self._loss]
            cursor = 0
            while True:
                if replayable:
                    if cursor >= len(feeds):
                        break
                    feed = feeds[cursor]
                else:
                    try:
                        feed = next(feeds)
                    except StopIteration:
                        break
                step = self._steps_run
                if self._faults is not None:
                    feed = self._faults.mutate_feed(step, feed)
                try:
                    fetches = self._dispatch(step, feed, fetch)
                except RetryBudgetExhausted as e:
                    self._abort("retry budget exhausted at step %d: %s"
                                % (step, e), cause=e)
                self._record_loss(fetches)
                self._steps_run += 1
                cursor += 1
                before = self._steps_run
                restored = self._maybe_rollback()
                if restored is not None and replayable:
                    cursor = max(0, cursor - (before - restored))
                self._maybe_checkpoint(self._steps_run)
            self._finalize()
        finally:
            # every exit path — including non-transient dispatch
            # errors retry_call re-raises directly — must disarm, or
            # the leaked watch turns into a guaranteed false stall on
            # the process watchdog once training stops
            self._disarm_hang_watch()
        return self.summary()

    def train_repeated(self, feed, iters, chunk=None, fetch_list=None):
        """Guarded driving of ``Executor.run_repeated``: ``iters`` steps
        of a FIXED feed dispatched in in-graph scan chunks. The anomaly
        guard runs inside the scan (bad steps self-skip on device,
        counters ride the scan carry); the host inspects the counters
        only at chunk boundaries, where it applies the same
        rollback/retry policy. ``chunk`` defaults to ``rollback_after``
        so a fully-poisoned chunk is caught before a second one
        dispatches."""
        enforce(int(iters) >= 1, "train_repeated needs iters >= 1")
        self._arm_hang_watch()
        try:
            self._ensure_initial_checkpoint()
            fetch = list(fetch_list) if fetch_list else [self._loss]
            chunk = int(chunk or max(1, self._rollback_after or 8))
            remaining = int(iters)
            while remaining > 0:
                k = min(chunk, remaining)
                step = self._steps_run

                def run_chunk():
                    if self._faults is not None:
                        self._faults.before_dispatch(step)
                    return self._exe.run_repeated(
                        self._program, feed=feed, fetch_list=fetch,
                        iters=k, scope=self._scope)

                try:
                    fetches, used = retry_call(run_chunk, self._retry,
                                               on_retry=self._on_retry)
                    self._retries += used
                except RetryBudgetExhausted as e:
                    self._abort("retry budget exhausted at step %d: %s"
                                % (step, e), cause=e)
                self._record_loss(fetches)
                self._steps_run += k
                remaining -= k
                before = self._steps_run
                restored = self._maybe_rollback()
                if restored is not None:
                    remaining += before - restored
                self._maybe_checkpoint(self._steps_run)
            self._finalize()
        finally:
            self._disarm_hang_watch()  # see train(): no leaked watch
        return self.summary()

    def train_from_dataset(self, dataset, fetch_list=None):
        """Guarded twin of ``Executor.train_from_dataset``: iterate the
        industrial Dataset's batches through the guarded step. The
        batch stream is not replayable, so rollback restores state and
        continues forward (weights rewind, data does not)."""
        return self.train(dataset.batch_iterator(),
                          fetch_list=fetch_list)

    def summary(self) -> Dict:
        skipped, consec = guard_mod.read_counters(self._scope) \
            if self._guard_on else (0.0, 0.0)
        # registry mirror (gauges: in-graph counters rewind at
        # rollback, so last-read-wins is the honest shape)
        reg = _obs.registry()
        reg.gauge("guard_skipped_steps").set(
            self._skipped_host + skipped)
        reg.gauge("guard_consec_anomalies").set(consec)
        ckpts = self._saver.list_checkpoints() if self._saver else []
        return {
            "steps_run": self._steps_run,
            "skipped_steps": int(round(self._skipped_host + skipped)),
            "consecutive_anomalies": int(consec),
            "rollbacks": self._rollbacks,
            "retries": self._retries,
            "save_failures": self._save_failures,
            "final_loss": self._last_finite_loss,
            "losses": list(self._losses),
            "checkpoints": ckpts,
            "retry_schedule": [round(d, 4)
                               for d in self._retry.delays()],
            "aborted": self._aborted,
            "faults": self._faults.summary()
            if self._faults is not None else None,
        }

    # -- internals -----------------------------------------------------
    def _arm_hang_watch(self):
        """Arm the wedged-dispatch watch on the process watchdog: the
        executor's dispatch beacon must keep bumping while a dispatch
        is in flight. Pending is THIS executor's in-flight gap, so two
        trainers' executors never mask each other's wedge."""
        if self._hang_deadline_s is None or \
                self._health_watch is not None:
            return
        exe = self._exe
        if not hasattr(exe, "dispatch_beacon"):
            return
        self._health_watch = _obs.get_watchdog().watch(
            "guarded_dispatch", beacon=exe.dispatch_beacon,
            deadline_s=self._hang_deadline_s,
            pending_fn=exe.dispatch_inflight)

    def _disarm_hang_watch(self):
        if self._health_watch is not None:
            _obs.get_watchdog().unwatch(self._health_watch)
            self._health_watch = None

    def _dispatch(self, step, feed, fetch):
        def run_once():
            if self._faults is not None:
                self._faults.before_dispatch(step)
            return self._engine.run_step(self._program, feed,
                                         fetch_list=fetch,
                                         scope=self._scope,
                                         stages=self._stages)

        fetches, used = retry_call(run_once, self._retry,
                                   on_retry=self._on_retry)
        self._retries += used
        return fetches

    def _on_retry(self, attempt, exc, delay):
        _obs.emit("dispatch_retry", attempt=attempt, error=repr(exc),
                  delay_s=delay)
        _obs.registry().counter("guard_retries_total").inc()
        # a transient failure can strand donated device buffers in a
        # consumed state; a checkpoint restore heals the scope before
        # the retry re-dispatches (no-op for pre-dispatch failures)
        if "deleted" in str(exc).lower() and self._saver is not None:
            try:
                self._saver.restore_latest(self._exe)
            except Exception:
                pass

    def _record_loss(self, fetches):
        if not fetches:
            return
        v = float(np.asarray(fetches[0]).reshape(-1)[0])
        self._losses.append(v)
        if np.isfinite(v):
            self._last_finite_loss = v

    def _maybe_rollback(self):
        """Restore the latest complete checkpoint once K consecutive
        anomalous steps accumulate. Returns the restored step or
        None."""
        if not (self._guard_on and self._rollback_after
                and self._saver is not None):
            return None
        skipped, consec = guard_mod.read_counters(self._scope)
        if consec < self._rollback_after:
            return None
        if self._rollbacks >= self._max_rollbacks:
            self._abort(
                "anomaly persists after %d rollback(s) — %d "
                "consecutive non-finite steps" % (self._rollbacks,
                                                  int(consec)))
        # the counters are persistables too — the restore would rewind
        # them, so absorb the current tally into the host total first
        self._skipped_host += skipped
        self._saver.wait_quietly()
        # restore from BEFORE the poisoned window: a checkpoint saved
        # while steps were being skipped is finite (the guard protected
        # it) but replaying from it would silently drop the skipped
        # steps' batches; the window start is steps_run - consec
        window_start = max(0, self._steps_run - int(consec))
        restored = self._saver.restore_latest(self._exe,
                                              max_step=window_start)
        enforce(restored is not None,
                "rollback triggered but no complete checkpoint exists "
                "(the initial step-0 checkpoint should make this "
                "unreachable)")
        guard_mod.reset_guard_state(self._scope)
        # PRNG: the executor's run counter is monotonic and never
        # rewinds, so the replayed window draws fresh per-step keys —
        # "re-folding past the poisoned window" is structural. The
        # explicit bump documents the contract and separates the
        # streams even when a restore lands between scan chunks.
        self._exe._run_counter += 1
        self._rollbacks += 1
        self._steps_run = int(restored)
        _obs.emit("rollback", restored_step=int(restored),
                  consecutive_anomalies=int(consec),
                  rollbacks=self._rollbacks)
        _obs.registry().counter("guard_rollbacks_total").inc()
        return int(restored)

    def _maybe_checkpoint(self, step):
        if self._saver is None:
            return
        if self._checkpoint_every and \
                step % self._checkpoint_every == 0:
            if self._guard_on:
                # never checkpoint inside an anomaly window: the state
                # is finite (guarded) but a mid-window save wastes a
                # max_to_keep slot and can evict the pre-window
                # checkpoint the rollback needs
                _, consec = guard_mod.read_counters(self._scope)
                if consec > 0:
                    return
            self._save(step, sync=self._sync_saves)

    def _ensure_initial_checkpoint(self):
        """Guarantee the rollback invariant: a complete checkpoint at
        step <= steps_run always exists. An empty dir gets a
        synchronous step-0 save; a dir with prior checkpoints is
        RESUMED from (restore + adopt its step number) — otherwise a
        later rollback could only reach state newer than the poisoned
        window."""
        if self._saver is None or self._initial_ckpt_done:
            return
        if not self._saver.list_checkpoints():
            self._save(self._steps_run, sync=True)
        elif self._steps_run == 0:
            restored = self._saver.restore_latest(self._exe)
            if restored is not None:
                self._steps_run = int(restored)
                if self._guard_on:
                    guard_mod.reset_guard_state(self._scope)
        self._initial_ckpt_done = True

    def _save(self, step, sync):
        try:
            self._saver.save(step, sync=sync)
        except Exception:
            self._save_failures += 1
        if self._saver.take_write_error() is not None:
            self._save_failures += 1

    def _finalize(self):
        self._disarm_hang_watch()
        if self._saver is not None:
            self._save(self._steps_run, sync=True)
            self._saver.wait_quietly()
            if self._saver.take_write_error() is not None:
                self._save_failures += 1

    def _abort(self, reason, cause=None):
        self._disarm_hang_watch()
        if self._saver is not None:
            self._save(self._steps_run, sync=True)
        self._aborted = reason
        _obs.emit("training_aborted", reason=reason,
                  step=self._steps_run)
        err = TrainingAborted(reason, self.summary())
        # fatal-error black box: the abort report plus thread stacks /
        # journal tail / metric tail, when a dump dir is armed
        try:
            _obs.get_recorder().dump(
                "training_aborted", extra={"reason": reason,
                                           "step": self._steps_run})
        except Exception:
            pass
        if cause is not None:
            raise err from cause
        raise err
