"""In-graph anomaly detection: the ``anomaly_guard`` mode.

Reference analog: the Fluid runtime owns failure handling — the AMP
decorator's ``found_inf`` gate (contrib/mixed_precision/decorator.py)
skips the update when a scaled gradient overflows, but ONLY for AMP
programs. This module generalizes that gate to every run, including the
q8 quantized-collective path:

  - ``install_anomaly_guard(program)`` stamps a ``gate`` attr on every
    optimize-role op (the executor's select-instead-of-branch gating,
    executor._gate_result) and creates two persistable counters that
    ride the executor's persistable carry — including through the
    ``run_repeated`` scan, so a 1000-step in-graph run reports how many
    steps it skipped without a single host round-trip;
  - at trace time the executor builds an ``AnomalyGuardPlan`` that
    all-reduces an ``all_finite(loss, grads)`` flag from the raw
    gradients BEFORE the gradient collective runs (q8's int8 cast can
    launder a NaN block into garbage finite values, so checking the
    synced grads would miss the anomaly) and, AFTER it, rolls back the
    q8 error-feedback residuals on a bad step (a NaN residual would
    poison every subsequent step bit-by-bit) and advances the counters.

Everything is ``jnp.where``/select — XLA-friendly, fuses into the one
traced step, and costs one isfinite+reduce pass per gradient plus
select-gated optimizer writes: fixed O(#params) work per step,
batch-independent, measured by bench.py's ``guarded_step_overhead``
row (amortizes to <2% on compute-bound chip steps).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..core.enforce import enforce

# env key of the per-step all-finite flag (a trace value, never a
# program var: it exists only between the guard boundary and the gated
# optimizer ops of the same traced step)
FLAG_KEY = "__guard_all_finite__"

# persistable counters carried like optimizer accumulators (float32 [1],
# the same convention as AMP's loss_scaling_good_steps)
SKIPPED_VAR = "__guard_skipped_steps__"
CONSEC_VAR = "__guard_consec_anomalies__"


class AnomalyGuardPlan:
    """Trace-time plan: where and how to derive the all-finite flag and
    protect guarded state inside one traced step. Mirrors
    collectives.GradSyncPlan (same boundary: the first optimize-role op
    consuming a parameter gradient)."""

    def __init__(self, boundary: int, grad_keys: List[str],
                 residual_keys: List[str], loss_name: Optional[str],
                 compose_gates: Tuple[str, ...] = ()):
        self.boundary = boundary
        self.grad_keys = grad_keys
        self.residual_keys = residual_keys
        self.loss_name = loss_name
        # Accumulation mode (non-empty compose_gates = the update ops
        # already carry accumulation's ShouldApply gate): the guard
        # ZEROES the poisoned grads instead of skipping the update —
        # AMP's established overflow semantics. Freezing the whole
        # window would desynchronize it: the front-of-block counter
        # (which runs before the flag can exist) would roll over while
        # the accumulator kept its partial sum, and the next window
        # would apply a ~double-sized update. With zeroing, counter and
        # accumulator stay in lockstep and the window simply loses the
        # bad micro-step's contribution.
        self.compose_gates = compose_gates
        self.zero_grads = bool(compose_gates)
        # where post_sync fires. The guard's boundary can sit EARLIER
        # than the gradient collective's (its grad set includes
        # sparse-grad params the collective skips, and the optimizer
        # sorts params by name); the executor pins this to the sync
        # plan's boundary so residual protection and counter updates
        # always run AFTER the collective rewrote the residuals.
        self.post_boundary = boundary

    # -- executor hooks (run_block) ------------------------------------
    def pre_sync(self, env: Dict):
        """Before the gradient collective: compute the flag from the
        RAW grads (+ loss) and snapshot the q8 residuals the collective
        is about to overwrite."""
        from ..core.selected_rows import SparseRows
        flag = jnp.asarray(True)
        checked = list(self.grad_keys)
        if self.loss_name:
            checked.append(self.loss_name)
        for key in checked:
            v = env.get(key)
            if v is None:
                continue
            if isinstance(v, SparseRows):
                # sparse embedding grads: the VALUES slab is what the
                # scatter-update consumes, so that is what must be
                # finite
                v = v.values
            v = jnp.asarray(v)
            if not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            flag = jnp.logical_and(flag, jnp.all(jnp.isfinite(v)))
        env[FLAG_KEY] = flag
        for rkey in self.residual_keys:
            if rkey in env:
                env[("guard_res_snap", rkey)] = env[rkey]

    def post_sync(self, env: Dict):
        """After the collective: on a bad step restore the residuals to
        their pre-sync values (select, not branch) and advance the
        counters. The gated optimize ops downstream read FLAG_KEY."""
        from ..core.selected_rows import SparseRows
        flag = env[FLAG_KEY]
        for rkey in self.residual_keys:
            snap = env.pop(("guard_res_snap", rkey), None)
            if snap is not None and rkey in env:
                env[rkey] = jnp.where(flag, env[rkey], snap)
        if self.zero_grads:
            # accumulation mode (see __init__): zero the poisoned
            # grads so the window's counter/accumulator stay in sync
            for gkey in self.grad_keys:
                v = env.get(gkey)
                if v is None or isinstance(v, SparseRows):
                    continue
                v = jnp.asarray(v)
                if jnp.issubdtype(v.dtype, jnp.floating):
                    env[gkey] = jnp.where(flag, v, jnp.zeros_like(v))
        bad = 1.0 - flag.astype(jnp.float32)
        if SKIPPED_VAR in env:
            env[SKIPPED_VAR] = env[SKIPPED_VAR] + bad
        if CONSEC_VAR in env:
            env[CONSEC_VAR] = jnp.where(
                flag, jnp.zeros_like(env[CONSEC_VAR]),
                env[CONSEC_VAR] + 1.0)


def _guard_entries(block) -> Tuple[Optional[int], List[str], List[str]]:
    """(boundary, grad_keys, residual_keys) for a block — the same
    boundary rule as collectives.make_plan so the guard and the
    gradient collective interleave at one point."""
    from ..framework import Parameter, grad_var_name
    from ..parallel.collectives import residual_name
    params = [p for p in block.vars.values()
              if isinstance(p, Parameter)
              and getattr(p, "trainable", True)]
    grad_keys = sorted(grad_var_name(p.name) for p in params)
    boundary = None
    gset = set(grad_keys)
    for i, op in enumerate(block.ops):
        if op.attrs.get("op_role") == "optimize" and \
                any(n in gset for n in op.input_arg_names):
            boundary = i
            break
    residual_keys = sorted(residual_name(p.name) for p in params)
    return boundary, grad_keys, residual_keys


def _compose_gates(block, boundary) -> Tuple[str, ...]:
    """Gate vars that optimize-role ops at/after the boundary already
    carry (gradient accumulation's ShouldApply)."""
    seen = []
    for op in block.ops[boundary:]:
        g = op.attrs.get("gate")
        if op.attrs.get("op_role") == "optimize" and g \
                and g != FLAG_KEY and g not in seen:
            seen.append(g)
    return tuple(seen)


def make_plan(block, cfg) -> Optional[AnomalyGuardPlan]:
    """Build the trace-time plan for an installed guard, or None when
    the block has no optimizer consuming parameter grads (forward-only
    clones guard nothing — their optimize ops were pruned)."""
    boundary, grad_keys, residual_keys = _guard_entries(block)
    if boundary is None or not grad_keys:
        return None
    return AnomalyGuardPlan(boundary, grad_keys, residual_keys,
                            cfg.get("loss"),
                            _compose_gates(block, boundary))


def install_anomaly_guard(program, loss=None, scope=None):
    """Compile anomaly detection into ``program``'s traced step.

    Idempotent. Mutates the program once (bumping its version, so every
    executor cache recompiles):

      - every optimize-role op gains ``gate=FLAG_KEY`` — on a
        non-finite step its in-place state writes (ParamOut, moments,
        beta pows, lr counters) keep their previous values via
        ``jnp.where`` (executor._gate_result);
      - ``SKIPPED_VAR`` / ``CONSEC_VAR`` are created as persistable
        block vars and zero-filled in ``scope`` so the executor's
        persistable carry (including the run_repeated scan carry)
        picks them up from the first compiled step.

    ``loss``: optional loss Variable/name folded into the flag — a
    non-finite loss with finite grads (e.g. a poisoned metric head)
    still skips the step.
    """
    from ..core.scope import global_scope
    from ..framework import Variable
    if getattr(program, "_anomaly_guard", None) is not None:
        # already installed (this process, or a from_dict round-trip):
        # still make sure THIS scope carries the counters — a fresh
        # Scope would otherwise silently train with skip accounting
        # and rollback disabled — without zeroing a scope that is
        # already mid-run
        ensure_guard_state(scope or global_scope())
        # a loss supplied now upgrades a config that lacked one (the
        # legacy from_dict sniff path pins loss=None); version bump so
        # cached compiled steps pick up the added check
        if loss is not None and \
                program._anomaly_guard.get("loss") is None:
            program._anomaly_guard["loss"] = loss.name \
                if isinstance(loss, Variable) else loss
            program._bump()
        return program
    block = program.global_block()
    boundary, grad_keys, _res = _guard_entries(block)
    enforce(boundary is not None,
            "install_anomaly_guard needs a training program (no "
            "optimize-role op consumes a parameter gradient here); "
            "build the optimizer before installing the guard")
    if isinstance(loss, Variable):
        loss = loss.name
    # Only ops at/after the boundary can be gated: the flag is derived
    # from the gradients, which exist only once backward has run. An
    # optimize-role op BEFORE the boundary (gradient accumulation's
    # front-of-block step counter) stays ungated. Ops that already
    # carry a gate (accumulation's ShouldApply) keep it — the plan ANDs
    # the flag into that gate var at the boundary instead.
    # With gradient accumulation the guard zeroes grads instead of
    # gating (AnomalyGuardPlan.__init__): grad_accumulate ops stay
    # ungated so a zeroed contribution flows through and the window
    # closes normally.
    has_accum = any(op.type == "grad_accumulate" for op in block.ops)
    for op in block.ops[boundary:]:
        if op.attrs.get("op_role") == "optimize" \
                and "gate" not in op.attrs \
                and not (has_accum and op.type == "grad_accumulate"):
            op.attrs["gate"] = FLAG_KEY
    for cname in (SKIPPED_VAR, CONSEC_VAR):
        if cname not in block.vars:
            block.create_var(name=cname, shape=(1,), dtype="float32",
                             persistable=True, stop_gradient=True)
        # old checkpoints predate these vars: restore default-fills
        # them instead of failing (io._ckpt_optional)
        block.vars[cname]._ckpt_optional = True
    scope = scope or global_scope()
    reset_guard_state(scope)
    program._anomaly_guard = {"loss": loss}
    program._bump()
    # debug/verify mode: prove the gate contract (every state-mutating
    # optimize op gated, no gate before the boundary) right after the
    # rewrite that establishes it
    from ..analysis import maybe_verify_rewrite
    maybe_verify_rewrite(program, "install_anomaly_guard")
    return program


def reset_guard_state(scope):
    """Zero both counters in ``scope`` (used at install, after a
    rollback, and by tests)."""
    for cname in (SKIPPED_VAR, CONSEC_VAR):
        scope.set_var(cname, jnp.zeros((1,), jnp.float32))


def ensure_guard_state(scope):
    """Create-if-absent (never reset) the counters in ``scope``."""
    for cname in (SKIPPED_VAR, CONSEC_VAR):
        if not scope.has_var(cname) or scope.find_var(cname) is None:
            scope.set_var(cname, jnp.zeros((1,), jnp.float32))


def read_counters(scope) -> Tuple[float, float]:
    """(skipped_steps, consecutive_anomalies) — host-side view of the
    in-graph counters; (0, 0) when the guard is not installed."""
    import numpy as np
    out = []
    for cname in (SKIPPED_VAR, CONSEC_VAR):
        v = scope.find_var(cname) if scope.has_var(cname) else None
        out.append(float(np.asarray(v).reshape(-1)[0])
                   if v is not None else 0.0)
    return out[0], out[1]
