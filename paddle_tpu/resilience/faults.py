"""Deterministic, seed-driven fault injection.

Every robustness claim in this subsystem is mechanically checkable: the
injector plants the exact failures the guarded trainer must survive —

  - ``nan_grad_at(step)``       poison one feed tensor with NaN so the
                                backward pass produces non-finite grads
                                at precisely that step (model-agnostic:
                                a NaN input NaNs the loss and every
                                gradient downstream);
  - ``transient_dispatch_at``   raise a PJRT-shaped UNAVAILABLE error
                                from the dispatch, ``times`` attempts
                                in a row (tests the retry classifier
                                and the backoff budget);
  - ``crash_save_at(step)``     kill the checkpoint writer after N data
                                files — the preemption/power-loss model
                                for the durability ordering in
                                ``io.CheckpointSaver._write`` (the crash
                                must strand an invisible tmp dir, never
                                a visible torn checkpoint).

Hooks are consumed by ``GuardedTrainer`` (``mutate_feed`` /
``before_dispatch`` / ``attach_saver``) and by ``tools/chaos_run.py``.
The injector records everything it does in ``events`` so a chaos run's
summary can prove the faults actually fired.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class InjectedDispatchError(ConnectionError):
    """Stand-in for a transient PJRT dispatch/transfer failure (the
    retry classifier treats it as transient by type AND by its
    UNAVAILABLE message, mirroring the real tunneled-backend error)."""


class SimulatedCrash(RuntimeError):
    """Stand-in for a process kill (SIGKILL/preemption) mid-operation.
    NOT transient: a killed writer doesn't come back."""


class FaultInjector:
    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rng = np.random.RandomState(self.seed)
        self._nan_feeds: Dict[int, Optional[str]] = {}
        self._dispatch: Dict[int, int] = {}
        self._crash_saves: Dict[int, int] = {}
        self.events: List[Tuple] = []

    # -- arming --------------------------------------------------------
    def nan_grad_at(self, *steps, feed_name: Optional[str] = None):
        """Poison the named (or first float, alphabetically) feed
        tensor at each given step — once per step."""
        for s in steps:
            self._nan_feeds[int(s)] = feed_name
        return self

    def transient_dispatch_at(self, step: int, times: int = 1):
        """Fail the first ``times`` dispatch attempts of ``step``."""
        self._dispatch[int(step)] = int(times)
        return self

    def crash_save_at(self, step: int, after_files: int = 1):
        """Kill the checkpoint write issued at ``step`` after
        ``after_files`` data files have reached the tmp dir."""
        self._crash_saves[int(step)] = int(after_files)
        return self

    # -- hooks ---------------------------------------------------------
    def mutate_feed(self, step: int, feed: Dict) -> Dict:
        if step not in self._nan_feeds:
            return feed
        name = self._nan_feeds.pop(step)
        if name is None:
            floats = sorted(
                k for k, v in feed.items()
                if np.issubdtype(np.asarray(v).dtype, np.floating))
            if not floats:
                return feed
            name = floats[0]
        arr = np.array(feed[name], dtype=np.asarray(feed[name]).dtype,
                       copy=True)
        # one seed-chosen element is enough — isfinite reduces over the
        # whole tensor, and a single NaN input poisons every grad it
        # touches (a full-NaN tensor would be an easier, less honest
        # test)
        flat = arr.reshape(-1)
        flat[int(self._rng.randint(flat.size))] = np.nan
        feed = dict(feed)
        feed[name] = arr
        self.events.append(("nan_grad", step, name))
        return feed

    def before_dispatch(self, step: int):
        """Raise if a dispatch fault is armed for this step (each call
        consumes one armed failure)."""
        remaining = self._dispatch.get(step, 0)
        if remaining > 0:
            self._dispatch[step] = remaining - 1
            self.events.append(("transient_dispatch", step))
            raise InjectedDispatchError(
                "UNAVAILABLE: injected transient dispatch failure "
                "(step %d)" % step)

    def attach_saver(self, saver):
        """Arm a CheckpointSaver: its per-file write hook raises
        SimulatedCrash once ``after_files`` files of a crash-armed
        step's checkpoint have been written (the writer thread dies
        exactly as a preempted process would — mid-tmp-dir)."""
        injector = self

        def hook(step, name, index):
            after = injector._crash_saves.get(int(step))
            if after is not None and index + 1 >= after:
                injector._crash_saves.pop(int(step))
                injector.events.append(("crash_save", int(step), name))
                raise SimulatedCrash(
                    "injected writer kill after %d file(s) of "
                    "ckpt-%d" % (index + 1, step))

        saver._write_file_hook = hook
        return saver

    # -- reporting -----------------------------------------------------
    def summary(self) -> Dict:
        return {
            "seed": self.seed,
            "events": [list(e) for e in self.events],
            "unfired": {
                "nan_grad": sorted(self._nan_feeds),
                "transient_dispatch": sorted(
                    s for s, n in self._dispatch.items() if n > 0),
                "crash_save": sorted(self._crash_saves),
            },
        }


def make_torn_checkpoint(dirname: str, step: int, marker: str,
                         nbytes: int = 64):
    """Craft the on-disk wreckage of a pre-durability-fix power loss: a
    marked checkpoint dir whose tensor files are truncated garbage.
    ``restore_latest`` must fall back past it (tests only — the fixed
    write ordering can no longer produce this shape, but old
    checkpoints in the wild can)."""
    import os
    d = os.path.join(dirname, "ckpt-%d" % step)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "torn_tensor"), "wb") as f:
        f.write(b"\x00" * nbytes)
    with open(os.path.join(d, marker), "w") as f:
        f.write(str(step))
    return d
