"""Deterministic network fault injection: a framed-TCP proxy.

The wire-level sibling of ``faults.FaultInjector``: where the injector
plants in-process failures (NaN grads, dispatch errors, writer kills),
this proxy sits between an ``RPCClient`` and an ``RPCServer`` (the
tensor_rpc framing, native/tensor_rpc.cpp) and injures the CONNECTION
itself, seed-driven and replayable:

  - ``drop_rate`` / ``drop_next``    swallow a request frame — the
                                     client's deadline must fire (no
                                     response ever comes);
  - ``delay_s``                      sleep before forwarding each
                                     request (latency / stall model);
  - ``blackhole(True)``              swallow everything until released
                                     (the hard-stall model: a peer that
                                     accepts bytes but answers nothing);
  - ``disconnect_after(n)``          forward n more frames, then reset
                                     both sides mid-conversation;
  - ``duplicate_next(n)``            forward the next n SEND/PUSH
                                     frames TWICE (the at-least-once
                                     network) — the server's sequence
                                     dedup must absorb the replay; the
                                     proxy swallows the extra response
                                     so the client stream stays framed;
  - ``corrupt_next(mode)``           replace the next request with a
                                     malformed frame: ``garbage`` (bad
                                     magic), ``torn`` (header promises
                                     more bytes than ever arrive, then
                                     FIN), ``oversize`` (payload_len
                                     past the server's 16 GiB sanity
                                     cap). The server must fail that
                                     one connection, not wedge or crash
                                     its drain loop.

The proxy is frame-aware in both directions (requests: magic|verb|
name_len|payload_len|name|payload; responses: magic|status|len|payload)
so faults hit whole frames, never split ones. Every fired fault is
recorded in ``events`` — chaos runs prove their faults actually fired,
exactly like FaultInjector.summary().
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from ..chaos import faultpoints as _faults

_REQ_HDR = struct.Struct("<IBHQ")   # magic, verb, name_len, payload_len
_RESP_HDR = struct.Struct("<IBQ")   # magic, status, payload_len
_MAGIC = 0x43505254

# verbs whose frames duplicate_next targets (idempotent-by-seq pushes)
_DUP_VERBS = (1, 6)  # SEND, PUSH_SPARSE


def _read_exact(sock, n) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class _ConnState:
    def __init__(self, client, upstream):
        self.client = client
        self.upstream = upstream
        self.swallow_responses = 0  # one per duplicated request
        self.mu = threading.Lock()
        self.dead = False

    def close(self):
        self.dead = True
        for s in (self.client, self.upstream):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class NetFaultProxy:
    def __init__(self, upstream: str, seed: int = 0,
                 listen_host: str = "127.0.0.1"):
        host, port = upstream.rsplit(":", 1)
        if host in ("localhost", ""):
            host = "127.0.0.1"
        self.upstream_addr = (host, int(port))
        self.seed = int(seed)
        self._rng = np.random.RandomState(self.seed)
        self._mu = threading.Lock()
        self.events: List[Tuple] = []
        # fault arming
        self.drop_rate = 0.0
        self.delay_s = 0.0
        self._blackhole = False
        self._drop_next = 0
        self._dup_next = 0
        self._corrupt_next: Optional[str] = None
        self._disconnect_after: Optional[int] = None
        # listener
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((listen_host, 0))
        self._lsock.listen(64)
        self.endpoint = "%s:%d" % (listen_host,
                                   self._lsock.getsockname()[1])
        self._stop = threading.Event()
        self._conns: List[_ConnState] = []
        self._accept_t = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._accept_t.start()

    # -- arming --------------------------------------------------------
    def set_drop_rate(self, p: float):
        with self._mu:
            self.drop_rate = float(p)
        return self

    def set_delay(self, seconds: float):
        with self._mu:
            self.delay_s = float(seconds)
        return self

    def blackhole(self, on: bool = True):
        with self._mu:
            self._blackhole = bool(on)
        return self

    def drop_next(self, n: int = 1):
        with self._mu:
            self._drop_next += int(n)
        return self

    def duplicate_next(self, n: int = 1):
        with self._mu:
            self._dup_next += int(n)
        return self

    def corrupt_next(self, mode: str = "garbage"):
        assert mode in ("garbage", "torn", "oversize"), mode
        with self._mu:
            self._corrupt_next = mode
        return self

    def disconnect_after(self, n_frames: int):
        with self._mu:
            self._disconnect_after = int(n_frames)
        return self

    def _event(self, *ev):
        self.events.append(ev)

    def summary(self):
        return {"seed": self.seed,
                "events": [list(e) for e in self.events]}

    # -- pumping -------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                cl, _ = self._lsock.accept()
            except OSError:
                return
            try:
                up = socket.create_connection(self.upstream_addr,
                                              timeout=10)
            except OSError:
                cl.close()
                continue
            cl.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            up.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            st = _ConnState(cl, up)
            with self._mu:
                self._conns.append(st)
            threading.Thread(target=self._pump_requests, args=(st,),
                             daemon=True).start()
            threading.Thread(target=self._pump_responses, args=(st,),
                             daemon=True).start()

    def _read_request(self, sock):
        hdr = _read_exact(sock, _REQ_HDR.size)
        if hdr is None:
            return None
        magic, verb, name_len, payload_len = _REQ_HDR.unpack(hdr)
        if magic != _MAGIC or payload_len > (1 << 34):
            return None  # client itself desynced; kill the conn
        rest = _read_exact(sock, name_len + payload_len)
        if rest is None:
            return None
        return hdr + rest, verb

    def _pump_requests(self, st):
        try:
            while not st.dead:
                got = self._read_request(st.client)
                if got is None:
                    break
                frame, verb = got
                action, extra = self._decide(verb)
                if action == "drop":
                    self._event("drop", verb)
                    continue
                if action == "corrupt":
                    self._send_corrupt(st, extra)
                    continue
                if action == "delay":
                    time.sleep(extra)
                try:
                    st.upstream.sendall(frame)
                    if action == "duplicate":
                        st.upstream.sendall(frame)
                        with st.mu:
                            st.swallow_responses += 1
                        self._event("duplicate", verb)
                except OSError:
                    break
                if action == "disconnect":
                    self._event("disconnect", verb)
                    break
        finally:
            st.close()

    def _decide(self, verb):
        """One locked decision per request frame (deterministic: the
        seeded RNG is consumed in arrival order).

        ARMED one-shot faults are journaled through the fault-point
        plane (``faultpoints.record`` — queue-only, safe under this
        lock) so chaos ledgers carry one uniform ``fault_injected``
        shape; steady-state ``drop_rate``/``delay_s`` noise is NOT —
        it models an unreliable wire, not a discrete injection, and
        would drown doctor's fault audit. Plans armed on the dynamic
        ``net.request`` point act here too (crash -> disconnect)."""
        with self._mu:
            planned = _faults.decide("net.request", verb=int(verb),
                                     upstream="%s:%d"
                                     % self.upstream_addr)
            if planned == "drop":
                return "drop", None
            if planned == "delay":
                return "delay", 0.05
            if planned == "crash":
                return "disconnect", None
            if planned == "dup" and verb in _DUP_VERBS:
                return "duplicate", None
            if self._corrupt_next is not None:
                mode, self._corrupt_next = self._corrupt_next, None
                _faults.record("net.corrupt", "drop", verb=int(verb),
                               mode=mode)
                return "corrupt", mode
            if self._blackhole:
                self._event("blackhole_drop", verb)
                return "drop", None
            if self._drop_next > 0:
                self._drop_next -= 1
                _faults.record("net.drop", "drop", verb=int(verb))
                return "drop", None
            if self.drop_rate > 0 and \
                    float(self._rng.rand()) < self.drop_rate:
                return "drop", None
            if self._dup_next > 0 and verb in _DUP_VERBS:
                self._dup_next -= 1
                _faults.record("net.duplicate", "dup",
                               verb=int(verb))
                return "duplicate", None
            if self._disconnect_after is not None:
                self._disconnect_after -= 1
                if self._disconnect_after <= 0:
                    self._disconnect_after = None
                    _faults.record("net.disconnect", "crash",
                                   verb=int(verb))
                    return "disconnect", None
            if self.delay_s > 0:
                return "delay", self.delay_s
            return "forward", None

    def _send_corrupt(self, st, mode):
        self._event("corrupt", mode)
        try:
            if mode == "garbage":
                st.upstream.sendall(b"\xde\xad\xbe\xef" * 8)
            elif mode == "oversize":
                st.upstream.sendall(
                    _REQ_HDR.pack(_MAGIC, 1, 4, 1 << 35) + b"name")
            elif mode == "torn":
                # header promises 1000 payload bytes, sends 10, FIN
                st.upstream.sendall(
                    _REQ_HDR.pack(_MAGIC, 1, 4, 1000) + b"name" +
                    b"\x00" * 10)
        except OSError:
            pass
        # the injured conversation cannot be resynced: reset both sides
        # so the client fails fast and reconnects
        st.close()

    def _pump_responses(self, st):
        try:
            while not st.dead:
                hdr = _read_exact(st.upstream, _RESP_HDR.size)
                if hdr is None:
                    break
                magic, status, plen = _RESP_HDR.unpack(hdr)
                if magic != _MAGIC or plen > (1 << 34):
                    break
                payload = _read_exact(st.upstream, plen) if plen else b""
                if payload is None:
                    break
                swallow = False
                with st.mu:
                    if st.swallow_responses > 0:
                        st.swallow_responses -= 1
                        swallow = True
                if swallow:
                    self._event("swallow_dup_response", status)
                    continue
                try:
                    st.client.sendall(hdr + payload)
                except OSError:
                    break
        finally:
            st.close()

    def close(self):
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._mu:
            conns = list(self._conns)
        for st in conns:
            st.close()
