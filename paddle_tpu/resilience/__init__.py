"""Guarded training: in-graph anomaly detection, auto-rollback,
retry/backoff, and a deterministic fault-injection harness.

Reference analog: the Fluid runtime's production failure handling —
checkpoint_notify machinery (distribute_transpiler.py:1612), PS RPC
retry loops — generalized for the one-traced-step TPU executor. See
docs/resilience.md for the policy tables and chaos-harness usage.
"""

from .guard import (CONSEC_VAR, FLAG_KEY, SKIPPED_VAR,  # noqa: F401
                    AnomalyGuardPlan, ensure_guard_state,
                    install_anomaly_guard, read_counters,
                    reset_guard_state)
from .retry import (RetryBudgetExhausted, RetryPolicy,  # noqa: F401
                    is_transient, retry_call)
from .faults import (FaultInjector, InjectedDispatchError,  # noqa: F401
                     SimulatedCrash, make_torn_checkpoint)
from .netfaults import NetFaultProxy  # noqa: F401
from .trainer import GuardedTrainer, TrainingAborted  # noqa: F401

__all__ = [
    "AnomalyGuardPlan", "install_anomaly_guard", "read_counters",
    "reset_guard_state", "ensure_guard_state",
    "FLAG_KEY", "SKIPPED_VAR", "CONSEC_VAR",
    "RetryPolicy", "RetryBudgetExhausted", "retry_call", "is_transient",
    "FaultInjector", "InjectedDispatchError", "SimulatedCrash",
    "make_torn_checkpoint", "NetFaultProxy",
    "GuardedTrainer", "TrainingAborted",
]
