"""Retry/backoff for transient dispatch and transfer failures.

Reference analog: the Fluid parameter-server runtime retries RPCs to a
restarting pserver (grpc_client retry loops, listen_and_serv's
reconnect) — the model script never sees a transient network burp. Here
the transient surface is PJRT: a tunneled backend's dispatch can fail
with UNAVAILABLE/DEADLINE_EXCEEDED (observed through bench.py's axon
runs), a device-to-host transfer can hit a reset connection. Those are
retryable; a shape mismatch or an OOM is not.

Classification is by exception TYPE NAME + message pattern, not by
``isinstance`` against jaxlib types — the jaxlib exception classes moved
modules across releases and may be absent entirely on stub backends, so
matching names keeps the classifier dependency-free.

Backoff is exponential with deterministic, seed-driven jitter (the
fault-injection harness demands reproducible schedules): attempt ``k``
sleeps ``min(max_delay, base * 2**k) * (1 + jitter * u_k)`` with ``u_k``
drawn from a ``numpy.random.RandomState(seed)``.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..core.enforce import EnforceNotMet

# Message substrings that mark an exception as transient when its type
# alone is ambiguous (XlaRuntimeError carries both transient and
# permanent gRPC codes).
TRANSIENT_MESSAGE_PATTERNS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "connection reset",
    "connection refused",
    "broken pipe",
    "socket closed",
    "failed to connect",
    "transfer to device failed",
    "transfer from device failed",
    # a dispatch that died AFTER donation consumed its input buffers
    # leaves the scope holding deleted arrays; the retry is viable
    # only because GuardedTrainer._on_retry restores the latest
    # checkpoint when it sees this pattern — classifying it permanent
    # would crash the run with no final checkpoint instead
    "has been deleted",
    "donated buffer",
)

# Exception type names that are transient regardless of message.
TRANSIENT_TYPE_NAMES = (
    "ConnectionError",
    "ConnectionResetError",
    "ConnectionAbortedError",
    "BrokenPipeError",
    "TimeoutError",
    "InjectedDispatchError",  # the fault harness's stand-in
)

# Type names that MAY be transient — decided by message pattern.
AMBIGUOUS_TYPE_NAMES = ("XlaRuntimeError", "RpcError", "OSError",
                        "RuntimeError")

# Structured TERMINAL outcomes of the distributed runtime: their
# messages contain words like ABORTED that would otherwise satisfy the
# pattern classifier, but retrying them is never correct (an evicted
# trainer stays evicted; an aborted barrier stays aborted).
PERMANENT_TYPE_NAMES = ("BarrierAborted", "TrainerEvicted",
                        "SimulatedCrash")


def is_transient(exc: BaseException) -> bool:
    """True when retrying the dispatch could plausibly succeed."""
    if isinstance(exc, EnforceNotMet):
        return False  # framework-detected misuse never heals by itself
    names = {t.__name__ for t in type(exc).__mro__}
    if names & set(PERMANENT_TYPE_NAMES):
        return False
    if names & set(TRANSIENT_TYPE_NAMES):
        return True
    if names & set(AMBIGUOUS_TYPE_NAMES):
        msg = str(exc).lower()
        return any(p.lower() in msg
                   for p in TRANSIENT_MESSAGE_PATTERNS)
    return False


class RetryPolicy:
    """Budgeted exponential backoff with deterministic jitter."""

    def __init__(self, max_retries: int = 3, base_delay: float = 0.5,
                 max_delay: float = 30.0, jitter: float = 0.25,
                 seed: int = 0,
                 classify: Callable[[BaseException], bool] = None):
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.classify = classify or is_transient

    def delays(self) -> List[float]:
        """The full deterministic backoff schedule (one delay per
        retry) — exposed so tests and the chaos report can print it."""
        rng = np.random.RandomState(self.seed)
        out = []
        for k in range(self.max_retries):
            d = min(self.max_delay, self.base_delay * (2.0 ** k))
            out.append(d * (1.0 + self.jitter * float(rng.rand())))
        return out


class RetryBudgetExhausted(RuntimeError):
    """All retries consumed. ``.attempts`` lists every failure."""

    def __init__(self, attempts):
        self.attempts = attempts
        last = attempts[-1][1] if attempts else None
        super().__init__(
            "retry budget exhausted after %d attempt(s); last: %r"
            % (len(attempts), last))


def retry_call(fn: Callable, policy: Optional[RetryPolicy] = None,
               on_retry: Callable[[int, BaseException, float], None]
               = None, sleep: Callable[[float], None] = time.sleep
               ) -> Tuple[object, int]:
    """Call ``fn`` with the policy's budget. Returns ``(result,
    retries_used)``. Non-transient exceptions propagate immediately;
    transient ones consume the budget and end in
    ``RetryBudgetExhausted`` (whose ``__cause__`` is the last
    failure)."""
    policy = policy or RetryPolicy()
    delays = policy.delays()
    attempts = []
    for attempt in range(policy.max_retries + 1):
        try:
            return fn(), attempt
        except BaseException as e:
            if not policy.classify(e):
                raise
            attempts.append((attempt, e))
            if attempt >= policy.max_retries:
                err = RetryBudgetExhausted(attempts)
                raise err from e
            delay = delays[attempt]
            if on_retry is not None:
                on_retry(attempt, e, delay)
            if delay > 0:
                sleep(delay)
