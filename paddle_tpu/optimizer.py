"""Optimizers — graph-building front-end over ops/optimizer_ops.py.

Reference: python/paddle/fluid/optimizer.py (Optimizer:50, minimize:565 =
backward:441 + apply_gradients:499, _create_optimization_pass:339
creating accumulators + per-param update ops; 12 concrete optimizers
SGD:608 ... Lamb:2074).

The structure is preserved: optimizer state (moments, beta powers) are
persistable vars; ``minimize`` appends backward ops then one update op
per parameter. On TPU all updates live in the same XLA program as the
step, so the reference's fuse_all_optimizer_ops pass
(fuse_optimizer_ops_pass/) is unnecessary — XLA fuses them.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import framework, unique_name
from .backward import append_backward
from .core.enforce import enforce
from .framework import Variable, default_main_program, program_guard
from .layer_helper import LayerHelper
from .layers import tensor as tensor_layers
from .regularizer import append_regularization_ops


class Optimizer:
    """Reference: optimizer.py:50."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._learning_rate_map: Dict[int, Variable] = {}
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self.type = self.__class__.__name__.lower()

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        if id(program) in self._learning_rate_map:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[id(program)] = self._learning_rate
            return
        lr = tensor_layers.create_global_var(
            shape=(), value=float(self._learning_rate), dtype="float32",
            persistable=True,
            name=unique_name.generate("learning_rate"))
        self._learning_rate_map[id(program)] = lr

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(id(program))

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        param_lr = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if param_lr == 1.0:
            return base
        from .layers import nn
        return nn.scale(base, scale=float(param_lr))

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = tuple(shape if shape is not None else param.shape)
        var = tensor_layers.create_global_var(
            shape=shape, value=float(fill_value),
            dtype=dtype or param.dtype, persistable=True,
            name=unique_name.generate(param.name + "_" + name))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- abstract per-optimizer hook ---------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # -- public API --------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               callbacks)

    def apply_gradients(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        block = default_main_program().global_block()
        self._create_global_learning_rate()
        self._create_accumulators(
            block, [p for p, g in params_grads if g is not None])
        optimize_ops = []
        for pg in params_grads:
            if pg[1] is None:
                continue
            optimize_ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, params_grads)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        from . import dygraph
        if dygraph.enabled():
            # eager path: tape backward + in-place param updates via the
            # same optimizer op lowerings (dygraph/optimizer_eager.py)
            from .dygraph.optimizer_eager import apply_dygraph
            params_grads = apply_dygraph(self, loss, parameter_list,
                                         grad_clip=grad_clip)
            return [], params_grads
        params_grads = self.backward(loss, startup_program,
                                     parameter_list, no_grad_set)
        if grad_clip is not None:
            from .clip import append_gradient_clip_ops
            params_grads = append_gradient_clip_ops(params_grads,
                                                    grad_clip)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    """Reference: optimizer.py:608."""

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param]},
            attrs={"op_role": "optimize"})


class MomentumOptimizer(Optimizer):
    """Reference: optimizer.py Momentum."""

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="momentum",
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov,
                   "op_role": "optimize"})


class LarsMomentumOptimizer(Optimizer):
    """Reference: optimizer.py LarsMomentumOptimizer."""

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   "op_role": "optimize"})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon, "op_role": "optimize"})


class AdamOptimizer(Optimizer):
    """Reference: optimizer.py AdamOptimizer (adam_op.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=(),
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=(),
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="adam",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p],
                    "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "lazy_mode": self._lazy_mode,
                   "op_role": "optimize"})


class AdamWOptimizer(AdamOptimizer):
    """Decoupled weight decay (contrib
    extend_optimizer/decoupled_weight_decay analog)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         regularization, name)
        self._weight_decay = weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="adamw",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p],
                    "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay,
                   "op_role": "optimize"})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, shape=(),
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        inf_norm = self._get_accumulator("inf_norm", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        return block.append_op(
            type="adamax",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [b1p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment],
                     "InfNormOut": [inf_norm], "Beta1PowOut": [b1p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "op_role": "optimize"})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon,
                   "op_role": "optimize"})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", param)
        asu = self._get_accumulator("avg_squared_update", param)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"rho": self._rho, "epsilon": self._epsilon,
                   "op_role": "optimize"})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        mom = self._get_accumulator("momentum", param)
        ms = self._get_accumulator("mean_square", param)
        mg = self._get_accumulator("mean_grad", param)
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [param], "Grad": [grad], "Moment": [mom],
                    "MeanSquare": [ms], "MeanGrad": [mg],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [mom],
                     "MeanSquareOut": [ms], "MeanGradOut": [mg]},
            attrs={"rho": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum,
                   "centered": self._centered, "op_role": "optimize"})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power, "op_role": "optimize"})


class LambOptimizer(Optimizer):
    """Reference: optimizer.py:2074 LambOptimizer."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._weight_decay = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=(),
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=(),
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="lamb",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p],
                    "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay,
                   "op_role": "optimize"})


# fluid-style aliases (reference exports both names)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
