"""Optimizers — graph-building front-end over ops/optimizer_ops.py.

Reference: python/paddle/fluid/optimizer.py (Optimizer:50, minimize:565 =
backward:441 + apply_gradients:499, _create_optimization_pass:339
creating accumulators + per-param update ops; 12 concrete optimizers
SGD:608 ... Lamb:2074).

The structure is preserved: optimizer state (moments, beta powers) are
persistable vars; ``minimize`` appends backward ops then one update op
per parameter. On TPU all updates live in the same XLA program as the
step, so the reference's fuse_all_optimizer_ops pass
(fuse_optimizer_ops_pass/) is unnecessary — XLA fuses them.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional

from . import framework, unique_name
from .backward import append_backward
from .core.enforce import enforce
from .framework import Variable, default_main_program, program_guard
from .layer_helper import LayerHelper
from .layers import tensor as tensor_layers
from .regularizer import append_regularization_ops


class Optimizer:
    """Reference: optimizer.py:50."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._learning_rate_map: Dict[int, Variable] = {}
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._accumulate_steps = 1
        self.type = self.__class__.__name__.lower()

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        if id(program) in self._learning_rate_map:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[id(program)] = self._learning_rate
            return
        lr = tensor_layers.create_global_var(
            shape=(), value=float(self._learning_rate), dtype="float32",
            persistable=True,
            name=unique_name.generate("learning_rate"))
        self._learning_rate_map[id(program)] = lr

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(id(program))

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        param_lr = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if param_lr == 1.0:
            return base
        from .layers import nn
        return nn.scale(base, scale=float(param_lr))

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = tuple(shape if shape is not None else param.shape)
        var = tensor_layers.create_global_var(
            shape=shape, value=float(fill_value),
            dtype=dtype or param.dtype, persistable=True,
            name=unique_name.generate(param.name + "_" + name))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- abstract per-optimizer hook ---------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # -- public API --------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               callbacks)

    def _append_grad_accumulation(self, block, params_grads, k):
        """Gradient accumulation over ``k`` micro-steps — the TPU-native
        analog of the reference's batch-merge pass
        (framework/ir/multi_batch_merge_pass.cc): instead of replicating
        the fwd/bwd subgraph k times, ONE program keeps a per-param
        running-sum accumulator + a step counter, and the update ops are
        gated (the executor selects old vs updated state) so parameters
        and optimizer moments change only every k-th run."""
        counter = tensor_layers.create_global_var(
            shape=(), value=0.0, dtype="int32", persistable=True,
            name=unique_name.generate("grad_acc_counter"))
        helper = LayerHelper("grad_acc")
        should = helper.create_variable_for_type_inference(
            "bool", stop_gradient=True)
        # inserted at the FRONT of the block so the gate value exists
        # before any op that must be gated — including LR-schedule step
        # counters appended during forward construction
        block.append_op(
            type="accum_steps_counter", inputs={"Counter": [counter]},
            outputs={"CounterOut": [counter], "ShouldApply": [should]},
            attrs={"k": int(k), "op_role": "optimize"}, index=0)
        # LR schedules must advance once per APPLIED update, not once
        # per micro-step (the reference batch-merge pass gates the whole
        # optimize section, lr-decay ops included)
        for op in block.ops:
            if any("@LR_DECAY_COUNTER@" in n
                   for n in op.output_arg_names):
                op.attrs["gate"] = should.name
        new_pg = []
        for p, g in params_grads:
            if g is None:
                new_pg.append((p, g))
                continue
            acc = tensor_layers.create_global_var(
                shape=tuple(p.shape), value=0.0, dtype=g.dtype,
                persistable=True,
                name=unique_name.generate(p.name + "_grad_acc"))
            g_eff = block.create_var(
                name=unique_name.generate(g.name + ".window_mean"),
                shape=tuple(p.shape), dtype=g.dtype, stop_gradient=True)
            block.append_op(
                type="grad_accumulate",
                inputs={"Acc": [acc], "Grad": [g],
                        "ShouldApply": [should]},
                outputs={"AccOut": [acc], "GradOut": [g_eff]},
                attrs={"k": float(k), "op_role": "optimize"})
            new_pg.append((p, g_eff))
        return new_pg, should

    def apply_gradients(self, params_grads):
        # update machinery appended through layers.* helpers
        # (regularizers, clip, accumulation gates) must carry the
        # optimize role so clone(for_test=True) prunes it with the
        # backward ops it reads (framework.op_role_guard)
        with framework.op_role_guard(default_main_program(),
                                     "optimize"):
            return self._apply_gradients_impl(params_grads)

    def _apply_gradients_impl(self, params_grads):
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        block = default_main_program().global_block()
        gate = None
        if self._accumulate_steps > 1:
            params_grads, gate = self._append_grad_accumulation(
                block, params_grads, self._accumulate_steps)
        self._create_global_learning_rate()
        self._create_accumulators(
            block, [p for p, g in params_grads if g is not None])
        # subclasses that append EXTRA stateful ops (DGC's u/v + step
        # counter) must gate them too — exposed for _append_optimize_op
        self._accum_gate = gate
        optimize_ops = []
        for pg in params_grads:
            if pg[1] is None:
                continue
            op = self._append_optimize_op(block, pg)
            if gate is not None and op is not None:
                op.attrs["gate"] = gate.name
            optimize_ops.append(op)
        self._accum_gate = None
        self._finish_update(block, params_grads)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None, accumulate_steps=None):
        """``accumulate_steps=k`` applies the update once per k runs on
        the mean of the k gradients (static-graph mode only; gradient
        clipping then acts on each micro-gradient)."""
        from . import dygraph
        if dygraph.enabled():
            # eager path: tape backward + in-place param updates via the
            # same optimizer op lowerings (dygraph/optimizer_eager.py)
            from .dygraph.optimizer_eager import apply_dygraph
            params_grads = apply_dygraph(self, loss, parameter_list,
                                         grad_clip=grad_clip)
            return [], params_grads
        if accumulate_steps is None:
            self._accumulate_steps = 1
        else:
            enforce(int(accumulate_steps) >= 1,
                    "accumulate_steps must be >= 1")
            self._accumulate_steps = int(accumulate_steps)
        params_grads = self.backward(loss, startup_program,
                                     parameter_list, no_grad_set)
        if grad_clip is not None:
            from .clip import append_gradient_clip_ops
            with framework.op_role_guard(default_main_program(),
                                         "optimize"):
                params_grads = append_gradient_clip_ops(params_grads,
                                                        grad_clip)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    """Reference: optimizer.py:608."""

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param]},
            attrs={"op_role": "optimize"})


class MomentumOptimizer(Optimizer):
    """Reference: optimizer.py Momentum."""

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="momentum",
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov,
                   "op_role": "optimize"})


class LarsMomentumOptimizer(Optimizer):
    """Reference: optimizer.py LarsMomentumOptimizer."""

    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [param], "Grad": [grad],
                    "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum,
                   "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   "op_role": "optimize"})


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression momentum (reference: optimizer.py:786
    DGCMomentumOptimizer; details/sparse_all_reduce_op_handle.h;
    arXiv:1712.01887). Sparsifies each parameter's update to the
    top-(1 - sparsity) entries of the locally-accumulated
    momentum-corrected gradient; the residual accumulates until it
    matters. See the ``dgc`` op for the TPU-native formulation (the
    GSPMD psum replaces the NCCL sparse allreduce)."""

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, name=None):
        super().__init__(learning_rate, momentum, use_nesterov,
                         regularization, name)
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = int(rampup_step)
        self._sparsity = tuple(float(s) for s in sparsity)
        self._local_grad_clip_norm = local_grad_clip_norm
        self._num_trainers = num_trainers
        self._step_var = None

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        gate = getattr(self, "_accum_gate", None)
        if self._step_var is None:
            self._step_var = tensor_layers.create_global_var(
                shape=(), value=0.0, dtype="int32", persistable=True,
                name=unique_name.generate("dgc_step"))
            counter_op = block.append_op(
                type="cum_step_counter",
                inputs={"X": [self._step_var]},
                outputs={"Out": [self._step_var]},
                attrs={"op_role": "optimize"})
            if gate is not None:
                # under gradient accumulation the DGC step advances
                # once per APPLIED update, not per micro-step
                counter_op.attrs["gate"] = gate.name
        if self._local_grad_clip_norm is not None:
            clipped = block.create_var(
                name=unique_name.generate(grad.name + ".dgc_clip"),
                shape=tuple(param.shape), dtype=grad.dtype,
                stop_gradient=True)
            block.append_op(
                type="clip_by_norm", inputs={"X": [grad]},
                outputs={"Out": [clipped]},
                attrs={"max_norm":
                       float(self._local_grad_clip_norm) *
                       (float(self._num_trainers) ** -0.5
                        if self._num_trainers else 1.0),
                       "op_role": "optimize"})
            grad = clipped
        u = self._get_accumulator("dgc_u", param)
        v = self._get_accumulator("dgc_v", param)
        encoded = block.create_var(
            name=unique_name.generate(grad.name + ".dgc_encoded"),
            shape=tuple(param.shape), dtype=grad.dtype,
            stop_gradient=True)
        dgc_op = block.append_op(
            type="dgc",
            inputs={"U": [u], "V": [v], "Grad": [grad],
                    "CurrentStep": [self._step_var]},
            outputs={"UOut": [u], "VOut": [v],
                     "EncodedGrad": [encoded]},
            attrs={"m": self._momentum,
                   "sparsity": self._sparsity,
                   "rampup_begin_step": self._rampup_begin_step,
                   "rampup_step": self._rampup_step,
                   "use_nesterov": self._use_nesterov,
                   "op_role": "optimize"})
        if gate is not None:
            # u/v accumulators must only advance on the apply step
            dgc_op.attrs["gate"] = gate.name
        # momentum correction folded into u: the final apply is plain
        # sgd on the (sparse) encoded update
        return block.append_op(
            type="sgd",
            inputs={"Param": [param], "Grad": [encoded],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param]},
            attrs={"op_role": "optimize"})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None,
                 name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, regularization, name)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon, "op_role": "optimize"})


class AdamOptimizer(Optimizer):
    """Reference: optimizer.py AdamOptimizer (adam_op.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=(),
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=(),
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="adam",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p],
                    "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "lazy_mode": self._lazy_mode,
                   "op_role": "optimize"})


class AdamWOptimizer(AdamOptimizer):
    """Decoupled weight decay (contrib
    extend_optimizer/decoupled_weight_decay analog)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, regularization=None,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         regularization, name)
        self._weight_decay = weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="adamw",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p],
                    "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay,
                   "op_role": "optimize"})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, shape=(),
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        inf_norm = self._get_accumulator("inf_norm", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        return block.append_op(
            type="adamax",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [b1p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment],
                     "InfNormOut": [inf_norm], "Beta1PowOut": [b1p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "op_role": "optimize"})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad],
                    "Moment": [moment],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon,
                   "op_role": "optimize"})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        asg = self._get_accumulator("avg_squared_grad", param)
        asu = self._get_accumulator("avg_squared_update", param)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"rho": self._rho, "epsilon": self._epsilon,
                   "op_role": "optimize"})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        mom = self._get_accumulator("momentum", param)
        ms = self._get_accumulator("mean_square", param)
        mg = self._get_accumulator("mean_grad", param)
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [param], "Grad": [grad], "Moment": [mom],
                    "MeanSquare": [ms], "MeanGrad": [mg],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [mom],
                     "MeanSquareOut": [ms], "MeanGradOut": [mg]},
            attrs={"rho": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum,
                   "centered": self._centered, "op_role": "optimize"})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power, "op_role": "optimize"})


class LambOptimizer(Optimizer):
    """Reference: optimizer.py:2074 LambOptimizer."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self._weight_decay = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=(),
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=(),
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            type="lamb",
            inputs={"Param": [param], "Grad": [grad], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p],
                    "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(
                        param_and_grad)]},
            outputs={"ParamOut": [param], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon,
                   "weight_decay": self._weight_decay,
                   "op_role": "optimize"})


def _declare_persistable(block, var):
    """Declare an existing persistable var (by name) inside a fresh
    program so the executor binds it to the scope value — the pattern
    of reference io.py's _clone_var_in_block_."""
    return block.create_var(name=var.name, shape=tuple(var.shape),
                            dtype=var.dtype, persistable=True,
                            stop_gradient=True)


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging (reference: optimizer.py:2222
    ModelAverage + operators/average_accumulates_op). Construct AFTER
    optimizer.minimize: appends an average_accumulates op per parameter
    to the main program; ``apply()`` swaps parameters for their window
    average (eval), ``restore()`` swaps back."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None,
                 name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        main = default_main_program()
        block = main.global_block()
        self._params = [
            p for p in block.all_parameters()
            if p.trainable
            and getattr(p, "do_model_average", None) is not False]
        for p in self._params:
            self._create_accumulators(block, [p])
            self._append_average_accumulate_op(block, p)
        self._build_programs()

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("sum_1", p)
            self._add_accumulator("sum_2", p)
            self._add_accumulator("sum_3", p)
            self._add_accumulator("num_accumulates", p, dtype="int64",
                                  shape=())
            self._add_accumulator("old_num_accumulates", p,
                                  dtype="int64", shape=())
            self._add_accumulator("num_updates", p, dtype="int64",
                                  shape=())

    def _acc_vars(self, p):
        return [self._get_accumulator(n, p)
                for n in ("sum_1", "sum_2", "sum_3", "num_accumulates",
                          "old_num_accumulates", "num_updates")]

    def _append_average_accumulate_op(self, block, param):
        s1, s2, s3, na, ona, nu = self._acc_vars(param)
        block.append_op(
            type="average_accumulates",
            inputs={"Param": [param], "Sum1": [s1], "Sum2": [s2],
                    "Sum3": [s3], "NumAccumulates": [na],
                    "OldNumAccumulates": [ona], "NumUpdates": [nu]},
            outputs={"Sum1Out": [s1], "Sum2Out": [s2], "Sum3Out": [s3],
                     "NumAccumulatesOut": [na],
                     "OldNumAccumulatesOut": [ona],
                     "NumUpdatesOut": [nu]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window,
                   "op_role": "optimize"})

    def _build_programs(self):
        self._apply_program = framework.Program()
        ab = self._apply_program.global_block()
        self._restore_program = framework.Program()
        rb = self._restore_program.global_block()
        for p in self._params:
            pv = _declare_persistable(ab, p)
            accs = [_declare_persistable(ab, v)
                    for v in self._acc_vars(p)]
            backup = ab.create_var(
                name=p.name + ".model_avg_backup", shape=tuple(p.shape),
                dtype=p.dtype, persistable=True, stop_gradient=True)
            ab.append_op(type="assign", inputs={"X": [pv]},
                         outputs={"Out": [backup]})
            ab.append_op(
                type="model_average_apply",
                inputs={"Sum1": [accs[0]], "Sum2": [accs[1]],
                        "Sum3": [accs[2]], "NumAccumulates": [accs[3]],
                        "OldNumAccumulates": [accs[4]]},
                outputs={"Out": [pv]})
            rpv = _declare_persistable(rb, p)
            rbk = _declare_persistable(rb, backup)
            rb.append_op(type="assign", inputs={"X": [rbk]},
                         outputs={"Out": [rpv]})

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError(
            "ModelAverage is not a training optimizer; construct it "
            "after optimizer.minimize")

    @contextmanager
    def apply(self, executor, need_restore=True):
        """Swap params for their averages within the context."""
        executor.run(self._apply_program)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor):
        executor.run(self._restore_program)


class ExponentialMovingAverage:
    """EMA of parameters with bias correction (reference:
    optimizer.py:2412). Call ``update()`` after optimizer.minimize to
    append shadow updates to the main program; ``apply()`` swaps in the
    bias-corrected shadow values for evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._name = name or ""
        main = default_main_program()
        block = main.global_block()
        self._params = [p for p in block.all_parameters() if p.trainable]
        self._ema = {}
        for p in self._params:
            self._ema[p.name] = tensor_layers.create_global_var(
                shape=tuple(p.shape), value=0.0, dtype=p.dtype,
                persistable=True,
                name=unique_name.generate(p.name + ".ema"))
        self._decay_pow = tensor_layers.create_global_var(
            shape=(), value=1.0, dtype="float32", persistable=True,
            name=unique_name.generate(self._name + "ema_decay_pow"))
        self._build_programs()

    def update(self):
        block = default_main_program().global_block()
        helper = LayerHelper("ema")
        use_thres = self._thres_steps is not None
        for i, p in enumerate(self._params):
            ema = self._ema[p.name]
            inputs = {"Param": [p], "Ema": [ema],
                      "DecayPow": [self._decay_pow]}
            if use_thres:
                inputs["Step"] = [self._thres_steps]
            # decay_pow is shared (the decay schedule is global): only
            # the first op commits it; the rest discard the output
            dp_out = self._decay_pow if i == 0 else \
                helper.create_variable_for_type_inference(
                    "float32", stop_gradient=True)
            block.append_op(
                type="ema_update", inputs=inputs,
                outputs={"EmaOut": [ema], "DecayPowOut": [dp_out]},
                attrs={"decay": self._decay, "use_thres": use_thres,
                       "op_role": "optimize"})

    def _build_programs(self):
        self._apply_program = framework.Program()
        ab = self._apply_program.global_block()
        self._restore_program = framework.Program()
        rb = self._restore_program.global_block()
        for p in self._params:
            pv = _declare_persistable(ab, p)
            ev = _declare_persistable(ab, self._ema[p.name])
            dpv = _declare_persistable(ab, self._decay_pow)
            backup = ab.create_var(
                name=p.name + ".ema_backup", shape=tuple(p.shape),
                dtype=p.dtype, persistable=True, stop_gradient=True)
            ab.append_op(type="assign", inputs={"X": [pv]},
                         outputs={"Out": [backup]})
            ab.append_op(type="ema_apply",
                         inputs={"Ema": [ev], "DecayPow": [dpv]},
                         outputs={"Out": [pv]})
            rpv = _declare_persistable(rb, p)
            rbk = _declare_persistable(rb, backup)
            rb.append_op(type="assign", inputs={"X": [rbk]},
                         outputs={"Out": [rpv]})

    @contextmanager
    def apply(self, executor, need_restore=True):
        executor.run(self._apply_program)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor):
        executor.run(self._restore_program)


# fluid-style aliases (reference exports both names)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
DGCMomentum = DGCMomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
