"""Program debugging helpers.

Reference: python/paddle/fluid/debugger.py:1-275
(pprint_program_codes / pprint_block_codes / draw_block_graphviz).
The reference renders ProgramDesc protobufs; here the same entry
points render this framework's Program/Block objects — pseudo-code
text for reading, graphviz dot via the IR GraphVizPass for drawing.
"""

from __future__ import annotations

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def pprint_block_codes(block, show_backward=False):
    """One block as readable pseudo-code (reference
    debugger.py:pprint_block_codes). Returns the string (the
    reference prints; returning composes better and the caller can
    print)."""
    lines = []
    for var in sorted(block.vars.values(), key=lambda v: v.name):
        if not show_backward and "@GRAD" in var.name:
            continue
        tag = []
        if var.persistable:
            tag.append("persist")
        if getattr(var, "stop_gradient", False):
            tag.append("stop_grad")
        lines.append("var %s : %s%s %s" % (
            var.name, var.dtype,
            list(var.shape) if var.shape is not None else "?",
            ("[" + ",".join(tag) + "]") if tag else ""))
    for op in block.ops:
        if not show_backward and \
                op.attrs.get("op_role") == "backward":
            continue
        ins = ", ".join("%s=%s" % (slot, names)
                        for slot, names in sorted(op.inputs.items()))
        outs = ", ".join("%s=%s" % (slot, names)
                         for slot, names in sorted(op.outputs.items()))
        attrs = {k: v for k, v in op.attrs.items()
                 if k not in ("op_role", "op_namescope")}
        lines.append("%s <- %s(%s)%s" % (
            outs, op.type, ins,
            (" " + repr(attrs)) if attrs else ""))
    return "\n".join(lines)


def pprint_program_codes(program, show_backward=False):
    """Whole program, block by block (reference
    debugger.py:pprint_program_codes)."""
    chunks = []
    for i, block in enumerate(program.blocks):
        chunks.append("-- block %d %s" % (i, "-" * 40))
        chunks.append(pprint_block_codes(block, show_backward))
    text = "\n".join(chunks)
    print(text)
    return text


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Graphviz dot for one block (reference
    debugger.py:draw_block_graphviz) via the IR graph + GraphVizPass.
    ``highlights`` is accepted for signature parity (the dot already
    colors op vs var vs persistable nodes)."""
    del highlights
    from .ir import Graph
    from .ir.passes import GraphVizPass
    g = Graph(block.program, block.idx)
    GraphVizPass().set("path", path).apply(g)
    return path
