"""Industrial Dataset — file-sharded, multi-threaded training input.

Reference: paddle/fluid/framework/data_set.h:40 (Dataset/DatasetImpl:
LoadIntoMemory/LocalShuffle/GlobalShuffle:128-131), data_feed.h:353
(MultiSlotDataFeed text format: per slot "n v1 ... vn"),
dataset_factory.cc, python/paddle/fluid/dataset.py (DatasetFactory,
InMemoryDataset, QueueDataset).

TPU-native redesign:

- **Multi-threaded loading stays on the host** (I/O-bound; the GIL is
  released inside file reads and the native recordio scanner), feeding
  padded numpy batches to the one-XLA-program step.
- **Global shuffle is a deterministic hash partition**, not an RPC
  exchange: every worker reads the same filelist, then keeps the
  instances hashing to its rank — the same post-shuffle partition the
  reference reaches by shuffling records *between* nodes through the
  fleet RPC fabric (data_set.h:83), with zero communication. (For
  datasets too large to scan per worker, pre-shard the filelist and
  use local_shuffle.)
- Files ending in ``.rio``/``.recordio`` read through the
  fault-tolerant chunked container (recordio.py, C++ scanner);
  anything else is treated as MultiSlot text, one instance per line.
"""

from __future__ import annotations

import hashlib
import queue as queue_mod
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .core.enforce import InvalidArgumentError, enforce
from .recordio import Scanner

_ms_lib = None
_ms_lock = threading.Lock()


def _multislot_lib():
    """The native MultiSlot parser (native/multislot.cpp — the
    data_feed.cc tokenizer), or None when no toolchain exists."""
    global _ms_lib
    with _ms_lock:
        if _ms_lib is None:
            import ctypes

            from .native import load_library
            lib = load_library("multislot.cpp")
            if lib is None:
                _ms_lib = False
            else:
                lib.ms_parse_file.restype = ctypes.c_int64
                lib.ms_parse_file.argtypes = [
                    ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
                lib.ms_error.restype = ctypes.c_char_p
                lib.ms_error.argtypes = [ctypes.c_int64]
                lib.ms_num_instances.restype = ctypes.c_int64
                lib.ms_num_instances.argtypes = [ctypes.c_int64]
                lib.ms_slot_lens.restype = \
                    ctypes.POINTER(ctypes.c_int32)
                lib.ms_slot_lens.argtypes = [ctypes.c_int64,
                                             ctypes.c_int]
                lib.ms_slot_size.restype = ctypes.c_int64
                lib.ms_slot_size.argtypes = [ctypes.c_int64,
                                             ctypes.c_int]
                lib.ms_slot_floats.restype = \
                    ctypes.POINTER(ctypes.c_float)
                lib.ms_slot_floats.argtypes = [ctypes.c_int64,
                                               ctypes.c_int]
                lib.ms_slot_ints.restype = \
                    ctypes.POINTER(ctypes.c_int64)
                lib.ms_slot_ints.argtypes = [ctypes.c_int64,
                                             ctypes.c_int]
                lib.ms_free.argtypes = [ctypes.c_int64]
                _ms_lib = lib
    return _ms_lib or None

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset"]


class DatasetFactory:
    """Reference: dataset_factory.cc + python dataset.py
    DatasetFactory().create_dataset("InMemoryDataset")."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            cls = {"InMemoryDataset": InMemoryDataset,
                   "QueueDataset": QueueDataset}[datafeed_class]
        except KeyError:
            raise InvalidArgumentError(
                "unknown dataset class %r (InMemoryDataset | "
                "QueueDataset)" % datafeed_class)
        return cls()


class DatasetBase:
    """Reference: python dataset.py DatasetBase."""

    def __init__(self):
        self._filelist: List[str] = []
        self._batch_size = 1
        self._thread_num = 1
        self._use_vars = []
        self._parse_fn: Optional[Callable] = None
        self._seed = 0

    # -- configuration (reference API names) ---------------------------
    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size: int):
        enforce(batch_size > 0, "batch_size must be positive")
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        enforce(thread_num > 0, "thread_num must be positive")
        self._thread_num = int(thread_num)

    def set_use_var(self, var_list):
        """Declare the feed slots, in record order (reference:
        dataset.py set_use_var building the DataFeedDesc)."""
        self._use_vars = list(var_list)

    def set_parse_ins(self, fn: Callable):
        """Custom record parser: bytes/str -> list of numpy arrays
        (one per use_var). Overrides the MultiSlot text default."""
        self._parse_fn = fn

    def set_pipe_command(self, cmd):
        """The reference pipes every file through a shell command
        (data_feed.cc). Only the identity command is supported here —
        do preprocessing in set_parse_ins; silently dropping a real
        command would feed garbage bytes into training."""
        if cmd not in (None, "", "cat"):
            from .core.enforce import UnimplementedError
            raise UnimplementedError(
                "set_pipe_command(%r): shell preprocessing is not "
                "supported; express it as a parser via set_parse_ins"
                % (cmd,))
        self._pipe_command = cmd

    def set_hdfs_config(self, fs_name, fs_ugi):
        # vendor storage config accepted for API parity
        self._hdfs_config = (fs_name, fs_ugi)

    def set_seed(self, seed: int):
        self._seed = int(seed)

    # -- parsing -------------------------------------------------------
    def _parse_instance(self, line):
        """MultiSlot text: for each use_var, "<n> v1 ... vn"
        (reference: data_feed.h:351-353)."""
        if self._parse_fn is not None:
            return self._parse_fn(line)
        if isinstance(line, bytes):
            line = line.decode()
        toks = line.split()
        enforce(self._use_vars,
                "set_use_var must be called before loading")
        out = []
        i = 0
        for var in self._use_vars:
            enforce(i < len(toks), "truncated MultiSlot instance")
            n = int(toks[i])
            i += 1
            vals = toks[i:i + n]
            enforce(len(vals) == n, "truncated MultiSlot instance")
            i += n
            dtype = np.dtype(getattr(var, "dtype", "float32") or
                             "float32")
            if np.issubdtype(dtype, np.integer):
                out.append(np.asarray([int(v) for v in vals], dtype))
            else:
                out.append(np.asarray([float(v) for v in vals], dtype))
        # strict like the native parser (and the reference's CheckFile,
        # data_feed.cc): trailing tokens mean a slot-count mismatch
        enforce(i == len(toks),
                "MultiSlot instance has %d trailing tokens (more "
                "slots in the file than use_vars?)" % (len(toks) - i))
        return out

    def _read_file(self, path):
        if path.endswith((".rio", ".recordio")):
            yield from Scanner(path)
        else:
            with open(path, "rb") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield line

    def _parse_file_native(self, path):
        """Parse one MultiSlot text file with the C++ parser
        (native/multislot.cpp, the data_feed.cc analog). Returns the
        instance list, or None when the native library is unavailable
        (Python fallback). The ctypes call releases the GIL, so the
        reader THREAD POOL gets real parallelism here."""
        lib = _multislot_lib()
        if lib is None or not self._use_vars:
            return None
        import ctypes
        dtypes = [np.dtype(getattr(v, "dtype", "float32") or "float32")
                  for v in self._use_vars]
        n = len(dtypes)
        is_int = (ctypes.c_uint8 * n)(
            *(1 if np.issubdtype(d, np.integer) else 0
              for d in dtypes))
        h = lib.ms_parse_file(path.encode(), is_int, n)
        try:
            err = lib.ms_error(h)
            if err:
                raise InvalidArgumentError(
                    "%s: %s" % (path, err.decode()))
            count = lib.ms_num_instances(h)
            if count == 0:
                return []
            slots = []
            for s in range(n):
                lens = np.ctypeslib.as_array(
                    lib.ms_slot_lens(h, s), shape=(count,)).copy()
                size = lib.ms_slot_size(h, s)
                if size == 0:
                    # all-empty slot (sparse CTR): the arena is empty
                    # and its data() is NULL — don't dereference
                    vals = np.empty(0, dtypes[s])
                elif is_int[s]:
                    vals = np.ctypeslib.as_array(
                        lib.ms_slot_ints(h, s),
                        shape=(size,)).astype(dtypes[s], copy=True)
                else:
                    vals = np.ctypeslib.as_array(
                        lib.ms_slot_floats(h, s),
                        shape=(size,)).astype(dtypes[s], copy=True)
                offs = np.zeros(count + 1, np.int64)
                np.cumsum(lens, out=offs[1:])
                slots.append([vals[offs[i]:offs[i + 1]]
                              for i in range(count)])
            return [[slots[s][i] for s in range(n)]
                    for i in range(count)]
        finally:
            lib.ms_free(h)

    def _load_files_threaded(self, paths, emit):
        """Read ``paths`` with a thread pool (reference: the
        thread-per-DataFeed loading loop, data_set.h LoadIntoMemory);
        ``emit(instance)`` must be thread-safe."""
        work = queue_mod.Queue()
        for p in paths:
            work.put(p)
        errors = []

        def worker():
            while True:
                try:
                    p = work.get_nowait()
                except queue_mod.Empty:
                    return
                try:
                    native = None
                    if self._parse_fn is None and \
                            not p.endswith((".rio", ".recordio")):
                        native = self._parse_file_native(p)
                    if native is not None:
                        for inst in native:
                            emit(inst)
                    else:
                        for rec in self._read_file(p):
                            emit(self._parse_instance(rec))
                except Exception as e:  # surface in the caller
                    errors.append((p, e))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self._thread_num,
                                      max(len(paths), 1)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            p, e = errors[0]
            raise InvalidArgumentError(
                "failed loading %r: %s: %s" % (p, type(e).__name__, e))

    # -- batching ------------------------------------------------------
    def _batch_feed(self, instances):
        """Stack instances (lists of per-slot arrays) into a feed dict.
        Ragged slots right-pad with zeros to the var's DECLARED width
        when one is known — per-batch max widths would give every
        batch a different shape and force an XLA recompile each step
        (the LoD → padded+static redesign, SURVEY hard part 1)."""
        feed = {}
        for si, var in enumerate(self._use_vars):
            name = getattr(var, "name", "slot%d" % si)
            arrs = [ins[si] for ins in instances]
            width = max(a.shape[0] for a in arrs)
            shape = getattr(var, "shape", None)
            if shape:
                declared = shape[-1]
                if isinstance(declared, int) and declared > 0:
                    enforce(width <= declared,
                            "slot %r instance length %d exceeds the "
                            "declared width %d", name, width, declared)
                    width = declared
            if all(a.shape[0] == width for a in arrs):
                feed[name] = np.stack(arrs)
            else:
                out = np.zeros((len(arrs), width), arrs[0].dtype)
                for j, a in enumerate(arrs):
                    out[j, :a.shape[0]] = a
                feed[name] = out
        return feed

    def chunk_iterator(self, chunk_size, drop_last=True,
                       drop_last_chunk=False):
        """Yield ``(chunk_dict, n_batches)``: ``chunk_size``
        consecutive batches stacked along a NEW leading axis — the
        host-side feed format of ``Executor.run_pipelined`` (K steps
        per device dispatch). ``drop_last`` drops the final partial
        BATCH (as batch_iterator does); ``drop_last_chunk`` also drops
        a final partial chunk, keeping every chunk the same shape (one
        compiled scan, no tail-shape recompile). For background
        prefetch + device transfer use ``DevicePrefetcher`` over
        ``batch_iterator()`` instead — this is the synchronous
        building block (probe tools, no-prefetch baselines)."""
        # validate EAGERLY (a generator body would defer the error to
        # first iteration, far from the buggy call site)
        enforce(chunk_size >= 1, "chunk_size must be >= 1")

        from .pyreader import stack_batches

        def gen():
            buf = []
            for feed in self.batch_iterator(drop_last=drop_last):
                buf.append(feed)
                if len(buf) == chunk_size:
                    yield stack_batches(buf), len(buf)
                    buf = []
            if buf and not drop_last_chunk:
                yield stack_batches(buf), len(buf)

        return gen()


class InMemoryDataset(DatasetBase):
    """Load everything, shuffle, iterate (reference: dataset.py
    InMemoryDataset over data_set.h DatasetImpl)."""

    def __init__(self):
        super().__init__()
        self._instances = []
        self._loaded = False

    def load_into_memory(self):
        enforce(self._filelist, "set_filelist first")
        lock = threading.Lock()
        instances = []

        def emit(ins):
            with lock:
                instances.append(ins)

        self._load_files_threaded(self._filelist, emit)
        # thread completion order must not change the dataset: fix a
        # canonical order before any seeded shuffle
        self._instances = instances
        self._canonical_sort()
        self._loaded = True

    def _canonical_sort(self):
        def key(ins):
            h = hashlib.md5()
            for a in ins:
                h.update(a.tobytes())
            return h.digest()

        self._instances.sort(key=key)

    def local_shuffle(self):
        """Seeded in-memory shuffle (reference: data_set.h:128
        LocalShuffle)."""
        enforce(self._loaded, "load_into_memory first")
        rs = np.random.RandomState(self._seed)
        rs.shuffle(self._instances)

    def global_shuffle(self, fleet=None, thread_num=-1):
        """Deterministic cross-worker shuffle + partition (reference:
        data_set.h:83 GlobalShuffle exchanging records via fleet RPC).
        Every worker must have loaded the same filelist; each keeps
        the instances hashing to its rank, then locally shuffles."""
        enforce(self._loaded, "load_into_memory first")
        if fleet is None:
            rank, nranks = 0, 1
        else:
            rank, nranks = fleet.worker_index(), fleet.worker_num()
        if nranks > 1:
            kept = []
            for ins in self._instances:
                h = hashlib.md5(b"%d:" % self._seed)
                for a in ins:
                    h.update(a.tobytes())
                if int.from_bytes(h.digest()[:8], "little") \
                        % nranks == rank:
                    kept.append(ins)
            self._instances = kept
        self.local_shuffle()

    def release_memory(self):
        self._instances = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return len(self._instances)

    def batch_iterator(self, drop_last=True):
        enforce(self._loaded, "load_into_memory first")
        bs = self._batch_size
        for i in range(0, len(self._instances), bs):
            chunk = self._instances[i:i + bs]
            if len(chunk) < bs and drop_last:
                return
            yield self._batch_feed(chunk)


class QueueDataset(DatasetBase):
    """Streaming dataset: reader threads pump a bounded queue while
    training consumes (reference: dataset.py QueueDataset /
    MultiSlotDataFeed's PrivateQueueDataFeed). Abandoning the iterator
    early (break / exception in the train loop) stops and joins the
    reader threads — nothing blocks forever on the bounded queue."""

    QUEUE_CAPACITY = 4096

    def batch_iterator(self, drop_last=True):
        enforce(self._filelist, "set_filelist first")
        q = queue_mod.Queue(self.QUEUE_CAPACITY)
        stop = threading.Event()
        errors = []
        work = queue_mod.Queue()
        for p in self._filelist:
            work.put(p)

        def worker():
            while not stop.is_set():
                try:
                    p = work.get_nowait()
                except queue_mod.Empty:
                    return
                try:
                    for rec in self._read_file(p):
                        if stop.is_set():
                            return
                        ins = self._parse_instance(rec)
                        while not stop.is_set():
                            try:
                                q.put(ins, timeout=0.1)
                                break
                            except queue_mod.Full:
                                continue
                except Exception as e:
                    errors.append((p, e))
                    return

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self._thread_num,
                                      max(len(self._filelist), 1)))]
        for t in threads:
            t.start()

        try:
            buf = []
            while True:
                try:
                    item = q.get(timeout=0.1)
                except queue_mod.Empty:
                    if not any(t.is_alive() for t in threads):
                        break
                    continue
                buf.append(item)
                if len(buf) == self._batch_size:
                    yield self._batch_feed(buf)
                    buf = []
            # drain whatever landed between the last get and the
            # producers exiting
            while True:
                try:
                    buf.append(q.get_nowait())
                except queue_mod.Empty:
                    break
                if len(buf) == self._batch_size:
                    yield self._batch_feed(buf)
                    buf = []
            if errors:
                p, e = errors[0]
                raise InvalidArgumentError(
                    "failed streaming %r: %s: %s"
                    % (p, type(e).__name__, e))
            if buf and not drop_last:
                yield self._batch_feed(buf)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
