"""Composite network helpers (reference: python/paddle/fluid/nets.py —
simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """conv2d followed by pool2d (reference nets.py:28)."""
    conv_out = layers.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, stride=conv_stride,
                             padding=conv_padding, dilation=conv_dilation,
                             groups=conv_groups, param_attr=param_attr,
                             bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride,
                         pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   param_attr=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_stride=1,
                   pool_type="max", use_cudnn=True):
    """VGG-style conv block: N convs (+optional BN/dropout) then a pool
    (reference nets.py:119)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(v):
        if not hasattr(v, "__len__"):
            return [v] * len(conv_num_filter)
        return list(v)

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    for i, nf in enumerate(conv_num_filter):
        local_conv_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(tmp, num_filters=nf,
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr[i],
                            act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(tmp, dropout_prob=drop_rate)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def glu(input, dim=-1):
    """Gated linear unit: split in two along dim, a * sigmoid(b)
    (reference nets.py:312)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled-dot-product attention over [B, T, D] tensors
    (reference nets.py:350). Returns the context tensor [B, Tq, Dv]."""
    if num_heads < 1:
        raise ValueError("num_heads must be >= 1")

    def _split_heads(x):
        if num_heads == 1:
            return x
        s = x.shape
        x = layers.reshape(x, (-1, s[1], num_heads, s[2] // num_heads))
        return layers.transpose(x, perm=[0, 2, 1, 3])

    def _merge_heads(x):
        if num_heads == 1:
            return x
        x = layers.transpose(x, perm=[0, 2, 1, 3])
        s = x.shape
        return layers.reshape(x, (-1, s[1], s[2] * s[3]))

    q, k, v = (_split_heads(t) for t in (queries, keys, values))
    d_key = queries.shape[-1] // num_heads
    scaled_q = layers.scale(q, scale=d_key ** -0.5)
    logits = layers.matmul(scaled_q, k, transpose_y=True)
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    return _merge_heads(ctx)


def sequence_conv_pool(input, num_filters, filter_size,
                       param_attr=None, act="sigmoid",
                       pool_type="max", bias_attr=None,
                       seq_len=None):
    """sequence_conv -> sequence_pool (reference: nets.py
    sequence_conv_pool — the text-CNN building block).

    ``seq_len`` carries the padded-design lengths vector through both
    stages (the reference reads lengths from the LoD)."""
    conv = layers.sequence_conv(input, num_filters,
                                filter_size=filter_size,
                                param_attr=param_attr,
                                bias_attr=bias_attr, act=act,
                                seq_len=seq_len)
    return layers.sequence_pool(conv, pool_type, seq_len=seq_len)
