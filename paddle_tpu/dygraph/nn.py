"""Dygraph layer classes (reference: python/paddle/fluid/dygraph/nn.py
— Conv2D, Pool2D, FC, BatchNorm, Embedding, LayerNorm, GRUUnit...).
Each forward executes registered op lowerings eagerly via
run_dygraph_op, so dygraph and static graphs share one kernel
vocabulary."""

from __future__ import annotations

import numpy as np

from ..core.enforce import enforce
from .base import VarBase, run_dygraph_op
from .layers import Layer

__all__ = ["Conv2D", "Pool2D", "FC", "Linear", "BatchNorm",
           "Embedding", "LayerNorm", "GRUUnit", "Dropout",
           "Conv2DTranspose", "Conv3D", "Conv3DTranspose", "PRelu",
           "NCE", "BilinearTensorProduct", "GroupNorm",
           "SpectralNorm", "RowConv", "SequenceConv", "TreeConv"]


class Conv2D(Layer):
    def __init__(self, name_scope=None, num_channels=None,
                 num_filters=None, filter_size=3, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        ks = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        self._attrs = {"strides": stride, "paddings": padding,
                       "dilations": dilation, "groups": groups}
        self._act = act
        self.weight = self.create_parameter(
            shape=(num_filters, num_channels // groups) + tuple(ks),
            attr=param_attr)
        self.bias = self.create_parameter(shape=(num_filters,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        out = run_dygraph_op(
            "conv2d", {"Input": [x], "Filter": [self.weight]},
            dict(self._attrs))
        if self.bias is not None:
            out = run_dygraph_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]},
                {"axis": 1})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=2, pool_type="max",
                 pool_stride=2, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"pooling_type": pool_type, "ksize": pool_size,
                       "strides": pool_stride, "paddings": pool_padding,
                       "global_pooling": global_pooling,
                       "ceil_mode": ceil_mode}

    def forward(self, x):
        return run_dygraph_op("pool2d", {"X": [x]}, dict(self._attrs))


class FC(Layer):
    """Reference: dygraph/nn.py FC — projects [B, ...] to [B, size]."""

    def __init__(self, name_scope=None, size=None, num_flatten_dims=1,
                 param_attr=None, bias_attr=None, act=None,
                 input_dim=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._nfd = num_flatten_dims
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, in_features):
        self.weight = self.create_parameter(
            shape=(in_features, self._size), attr=self._param_attr)
        self.bias = self.create_parameter(
            shape=(self._size,), attr=self._bias_attr, is_bias=True)

    def forward(self, x):
        if self.weight is None:  # lazy build from first input
            in_features = 1
            for d in x.shape[self._nfd:]:
                in_features *= d
            self._build(in_features)
        out = run_dygraph_op(
            "mul", {"X": [x], "Y": [self.weight]},
            {"x_num_col_dims": self._nfd, "y_num_col_dims": 1})
        if self.bias is not None:
            out = run_dygraph_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]},
                {"axis": -1})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class Linear(FC):
    """2.x-style alias: Linear(in_features, out_features)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(None, size=output_dim, param_attr=param_attr,
                         bias_attr=bias_attr, act=act,
                         input_dim=input_dim, dtype=dtype)


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None,
                 is_test=False, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW"):
        super().__init__(name_scope, dtype)
        from .. import initializer as I
        self._attrs = {"momentum": momentum, "epsilon": epsilon,
                       "data_layout": data_layout}
        self._act = act
        self.weight = self.create_parameter(
            shape=(num_channels,), attr=param_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            shape=(num_channels,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean",
                             VarBase(np.zeros(num_channels,
                                              np.float32)))
        self.register_buffer("_variance",
                             VarBase(np.ones(num_channels,
                                             np.float32)))

    def forward(self, x):
        out, mean_out, var_out, _sm, _sv = run_dygraph_op(
            "batch_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            dict(self._attrs, is_test=not self.training))
        self._mean.value = mean_out.value
        self._variance.value = var_out.value
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 is_distributed=False, padding_idx=None,
                 param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx
        self.weight = self.create_parameter(shape=tuple(size),
                                            attr=param_attr)

    def forward(self, ids):
        return run_dygraph_op(
            "lookup_table", {"W": [self.weight], "Ids": [ids]},
            {"padding_idx": self._padding_idx})


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None,
                 scale=True, shift=True, begin_norm_axis=1,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        from .. import initializer as I
        n = 1
        shape = normalized_shape if isinstance(
            normalized_shape, (list, tuple)) else [normalized_shape]
        for d in shape:
            n *= d
        self._attrs = {"epsilon": epsilon,
                       "begin_norm_axis": begin_norm_axis}
        self._act = act
        self.weight = self.create_parameter(
            shape=(n,), attr=param_attr,
            default_initializer=I.Constant(1.0)) if scale else None
        self.bias = self.create_parameter(
            shape=(n,), attr=bias_attr, is_bias=True) if shift else None

    def forward(self, x):
        inputs = {"X": [x]}
        if self.weight is not None:
            inputs["Scale"] = [self.weight]
        if self.bias is not None:
            inputs["Bias"] = [self.bias]
        out, _m, _v = run_dygraph_op("layer_norm", inputs,
                                     dict(self._attrs))
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class GRUUnit(Layer):
    def __init__(self, name_scope=None, size=None, param_attr=None,
                 bias_attr=None, activation="tanh",
                 gate_activation="sigmoid", dtype="float32"):
        super().__init__(name_scope, dtype)
        enforce(size is not None and size % 3 == 0,
                "GRUUnit size must be 3*hidden")
        hidden = size // 3
        self._attrs = {"activation": activation,
                       "gate_activation": gate_activation}
        self.weight = self.create_parameter(
            shape=(hidden, 3 * hidden), attr=param_attr)
        self.bias = self.create_parameter(
            shape=(1, 3 * hidden), attr=bias_attr, is_bias=True)

    def forward(self, input, hidden):
        return run_dygraph_op(
            "gru_unit",
            {"X": [input], "HPrev": [hidden], "Weight": [self.weight],
             "Bias": [self.bias]},
            {"gate_activation": self._attrs["gate_activation"],
             "activation": self._attrs["activation"]})


class Dropout(Layer):
    def __init__(self, p=0.5):
        super().__init__(None)
        self._p = p

    def forward(self, x):
        if not self.training or self._p == 0:
            return x
        out, _mask = run_dygraph_op(
            "dropout", {"X": [x]},
            {"dropout_prob": self._p, "is_test": False,
             "dropout_implementation": "upscale_in_train"})
        return out


from ..core.shape_utils import pair as _pair  # noqa: E402
from ..core.shape_utils import triple as _triple  # noqa: E402


class Conv2DTranspose(Layer):
    """Reference: dygraph/nn.py Conv2DTranspose."""

    def __init__(self, name_scope=None, num_channels=None,
                 num_filters=None, filter_size=3, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"strides": _pair(stride),
                       "paddings": _pair(padding),
                       "dilations": _pair(dilation), "groups": groups}
        self._act = act
        self.weight = self.create_parameter(
            shape=(num_channels, num_filters // groups) +
            _pair(filter_size), attr=param_attr)
        self.bias = self.create_parameter(shape=(num_filters,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        out = run_dygraph_op(
            "conv2d_transpose", {"Input": [x], "Filter": [self.weight]},
            dict(self._attrs))
        if self.bias is not None:
            out = run_dygraph_op("elementwise_add",
                                 {"X": [out], "Y": [self.bias]},
                                 {"axis": 1})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class Conv3D(Layer):
    """Reference: dygraph/nn.py Conv3D (conv3d_op)."""

    def __init__(self, name_scope=None, num_channels=None,
                 num_filters=None, filter_size=3, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"strides": _triple(stride),
                       "paddings": _triple(padding),
                       "dilations": _triple(dilation), "groups": groups}
        self._act = act
        self.weight = self.create_parameter(
            shape=(num_filters, num_channels // groups) +
            _triple(filter_size), attr=param_attr)
        self.bias = self.create_parameter(shape=(num_filters,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        out = run_dygraph_op(
            "conv3d", {"Input": [x], "Filter": [self.weight]},
            dict(self._attrs))
        if self.bias is not None:
            out = run_dygraph_op("elementwise_add",
                                 {"X": [out], "Y": [self.bias]},
                                 {"axis": 1})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class Conv3DTranspose(Layer):
    def __init__(self, name_scope=None, num_channels=None,
                 num_filters=None, filter_size=3, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"strides": _triple(stride),
                       "paddings": _triple(padding),
                       "dilations": _triple(dilation), "groups": groups}
        self._act = act
        self.weight = self.create_parameter(
            shape=(num_channels, num_filters // groups) +
            _triple(filter_size), attr=param_attr)
        self.bias = self.create_parameter(shape=(num_filters,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        out = run_dygraph_op(
            "conv3d_transpose",
            {"Input": [x], "Filter": [self.weight]},
            dict(self._attrs))
        if self.bias is not None:
            out = run_dygraph_op("elementwise_add",
                                 {"X": [out], "Y": [self.bias]},
                                 {"axis": 1})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class PRelu(Layer):
    """Reference: dygraph/nn.py PRelu (mode all/channel/element)."""

    def __init__(self, name_scope=None, mode="all", channel=None,
                 input_shape=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        if mode == "all":
            shape = (1,)
        elif mode == "channel":
            shape = (channel,)
        else:
            shape = tuple(input_shape)
        self.weight = self.create_parameter(shape=shape,
                                            attr=param_attr)

    def forward(self, x):
        return run_dygraph_op("prelu",
                              {"X": [x], "Alpha": [self.weight]},
                              {"mode": self._mode})


class NCE(Layer):
    """Reference: dygraph/nn.py NCE."""

    def __init__(self, name_scope=None, num_total_classes=None, dim=None,
                 sample_weight=None, param_attr=None, bias_attr=None,
                 num_neg_samples=10, seed=0, dtype="float32"):
        super().__init__(name_scope, dtype)
        if sample_weight is not None:
            from ..core.enforce import UnimplementedError
            raise UnimplementedError(
                "NCE sample_weight is not supported (the nce op "
                "weights every example equally); drop the argument "
                "or weight the returned per-example cost yourself")
        self._attrs = {"num_total_classes": num_total_classes,
                       "num_neg_samples": num_neg_samples,
                       "seed": seed}
        self.weight = self.create_parameter(
            shape=(num_total_classes, dim), attr=param_attr)
        self.bias = self.create_parameter(shape=(num_total_classes,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, input, label):
        return run_dygraph_op(
            "nce", {"Input": [input], "Weight": [self.weight],
                    "Bias": [self.bias] if self.bias is not None
                    else [], "Label": [label]},
            dict(self._attrs))


class BilinearTensorProduct(Layer):
    def __init__(self, name_scope=None, size=None, x_dim=None,
                 y_dim=None, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter(shape=(size, x_dim, y_dim),
                                            attr=param_attr)
        self.bias = self.create_parameter(shape=(1, size),
                                          attr=bias_attr, is_bias=True)

    def forward(self, x, y):
        out = run_dygraph_op(
            "bilinear_tensor_product",
            {"X": [x], "Y": [y], "Weight": [self.weight],
             "Bias": [self.bias] if self.bias is not None else []},
            {})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class GroupNorm(Layer):
    def __init__(self, name_scope=None, channels=None, groups=1,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"groups": groups, "epsilon": epsilon}
        from .. import initializer as I
        self.weight = self.create_parameter(
            shape=(channels,), attr=param_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(shape=(channels,),
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        y, _mean, _var = run_dygraph_op(
            "group_norm",
            {"X": [x], "Scale": [self.weight], "Bias": [self.bias]},
            dict(self._attrs))
        return y


class SpectralNorm(Layer):
    def __init__(self, name_scope=None, weight_shape=None, dim=0,
                 power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"dim": dim, "power_iters": power_iters,
                       "eps": eps}
        h = weight_shape[dim]
        w_rest = 1
        for i, d in enumerate(weight_shape):
            if i != dim:
                w_rest *= d
        from .. import initializer as I
        self.weight_u = self.create_parameter(
            shape=(h,), default_initializer=I.Normal(0, 1))
        self.weight_v = self.create_parameter(
            shape=(w_rest,), default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        return run_dygraph_op(
            "spectral_norm",
            {"Weight": [weight], "U": [self.weight_u],
             "V": [self.weight_v]}, dict(self._attrs))


class RowConv(Layer):
    def __init__(self, name_scope=None, input_dim=None,
                 future_context_size=2, param_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter(
            shape=(future_context_size + 1, input_dim),
            attr=param_attr)

    def forward(self, x):
        out = run_dygraph_op("row_conv",
                             {"X": [x], "Filter": [self.weight]}, {})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class SequenceConv(Layer):
    def __init__(self, name_scope=None, input_dim=None, num_filters=None,
                 filter_size=3, param_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self._attrs = {"context_length": filter_size}
        self.weight = self.create_parameter(
            shape=(filter_size * input_dim, num_filters),
            attr=param_attr)

    def forward(self, x, lengths=None):
        out = run_dygraph_op(
            "sequence_conv",
            {"X": [x], "Filter": [self.weight],
             "Lengths": [lengths] if lengths is not None else []},
            dict(self._attrs))
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out


class TreeConv(Layer):
    """Reference: dygraph/nn.py TreeConv (TBCNN)."""

    def __init__(self, name_scope=None, feature_size=None,
                 output_size=None, num_filters=1, max_depth=2,
                 act="tanh", param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"max_depth": max_depth}
        self._act = act
        self.weight = self.create_parameter(
            shape=(feature_size, 3, output_size, num_filters),
            attr=param_attr)
        self.bias = self.create_parameter(
            shape=(1, 1, output_size, num_filters), attr=bias_attr,
            is_bias=True)

    def forward(self, nodes_vector, edge_set):
        out = run_dygraph_op(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self.weight]}, dict(self._attrs))
        if self.bias is not None:
            out = run_dygraph_op("elementwise_add",
                                 {"X": [out], "Y": [self.bias]}, {})
        if self._act:
            out = run_dygraph_op(self._act, {"X": [out]}, {})
        return out
