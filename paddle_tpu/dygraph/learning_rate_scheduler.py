"""Imperative learning-rate schedules.

Reference: python/paddle/fluid/dygraph/learning_rate_scheduler.py —
LearningRateDecay subclasses are CALLABLE learning rates: the
optimizer calls the object each step, which returns the current lr
and advances its counter. The TPU redesign returns plain Python
floats (the eager optimizers fold the lr into the jitted update as a
scalar operand; no 1-element persistable var is needed)."""

from __future__ import annotations

import math

__all__ = ["LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "NoamDecay"]


class LearningRateDecay:
    """Reference learning_rate_scheduler.py:27 — __call__ returns the
    lr for the CURRENT step then advances ``step_num`` by
    ``step_size``."""

    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def __call__(self):
        lr = float(self.step())
        self.step_num += self.step_size
        return lr

    def step(self):
        raise NotImplementedError()


class PiecewiseDecay(LearningRateDecay):
    """Reference :58 — values[i] while step < boundaries[i]."""

    def __init__(self, boundaries, values, begin, step=1,
                 dtype="float32"):
        super().__init__(begin, step, dtype)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    """Reference :75 — lr * exp(-rate * t)."""

    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def _div(self):
        d = self.step_num / self.decay_steps
        return math.floor(d) if self.staircase else d

    def step(self):
        return self.learning_rate * math.exp(
            -self.decay_rate * self._div())


class ExponentialDecay(NaturalExpDecay):
    """Reference :101 — lr * rate^(t/steps)."""

    def step(self):
        return self.learning_rate * (self.decay_rate ** self._div())


class InverseTimeDecay(NaturalExpDecay):
    """Reference :127 — lr / (1 + rate * t/steps)."""

    def step(self):
        return self.learning_rate / (1.0 + self.decay_rate
                                     * self._div())


class PolynomialDecay(LearningRateDecay):
    """Reference :153."""

    def __init__(self, learning_rate, decay_steps,
                 end_learning_rate=0.0001, power=1.0, cycle=False,
                 begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def step(self):
        t, steps = self.step_num, self.decay_steps
        if self.cycle:
            div = math.ceil(t / float(steps)) if t > 0 else 1.0
            steps = steps * div
        else:
            t = min(t, steps)
        return ((self.learning_rate - self.end_learning_rate)
                * (1 - t / steps) ** self.power
                + self.end_learning_rate)


class CosineDecay(LearningRateDecay):
    """Reference :191 — half-cosine over epochs."""

    def __init__(self, learning_rate, step_each_epoch, epochs,
                 begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def step(self):
        cur_epoch = math.floor(self.step_num / self.step_each_epoch)
        return self.learning_rate * 0.5 * (
            math.cos(cur_epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    """Reference :213 — the transformer warmup schedule."""

    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32"):
        super().__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def step(self):
        t = max(self.step_num, 1)
        return (self.d_model ** -0.5) * min(
            t ** -0.5, (self.warmup_steps ** -1.5) * t)
