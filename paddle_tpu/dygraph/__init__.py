"""Dygraph (eager/imperative) mode — reference:
paddle/fluid/imperative/ + python/paddle/fluid/dygraph/."""

from . import learning_rate_scheduler  # noqa: F401
from . import nn  # noqa: F401
from .backward_strategy import BackwardStrategy  # noqa: F401
from .learning_rate_scheduler import (CosineDecay,  # noqa: F401
                                      ExponentialDecay,
                                      InverseTimeDecay,
                                      LearningRateDecay,
                                      NaturalExpDecay, NoamDecay,
                                      PiecewiseDecay,
                                      PolynomialDecay)
from .base import (VarBase, backward, enabled, guard,  # noqa: F401
                   in_dygraph_mode, no_grad, run_dygraph_op,
                   to_variable)
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from .layers import Layer, Parameter  # noqa: F401
from .nn import (FC, BatchNorm, Conv2D, Dropout, Embedding,  # noqa: F401
                 GRUUnit, LayerNorm, Linear, Pool2D)
from .parallel import DataParallel, ParallelEnv  # noqa: F401
