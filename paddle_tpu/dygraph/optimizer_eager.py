"""Eager optimizer application for dygraph mode.

Reference: in dygraph the same fluid optimizers apply grads held on
VarBases (python/paddle/fluid/optimizer.py minimize under
imperative mode; imperative/layer.h:116). Here each optimizer's
update rule is the SAME registered op lowering the static path appends
(ops/optimizer_ops.py), executed eagerly with state kept on the
optimizer instance."""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from .. import ops
from ..core.enforce import UnimplementedError, enforce
from .base import VarBase


def _state(opt) -> Dict[int, dict]:
    if not hasattr(opt, "_dygraph_state"):
        opt._dygraph_state = {}
    return opt._dygraph_state


def _lr(opt):
    lr = opt._learning_rate
    if callable(lr):
        lr = lr()
    return jnp.float32(float(lr))


def _eager_clip(grad_clip, pairs):
    """Eager equivalents of the clip attrs (reference: clip.py)."""
    from .. import clip as C
    if isinstance(grad_clip, C.GradientClipByValue):
        return [(p, jnp.clip(g, grad_clip.min, grad_clip.max))
                for p, g in pairs]
    if isinstance(grad_clip, C.GradientClipByNorm):
        out = []
        for p, g in pairs:
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            out.append((p, g * jnp.minimum(1.0,
                                           grad_clip.clip_norm / n)))
        return out
    if isinstance(grad_clip, C.GradientClipByGlobalNorm):
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                             for _p, g in pairs))
        scale = grad_clip.clip_norm / jnp.maximum(
            total, grad_clip.clip_norm)
        return [(p, g * scale) for p, g in pairs]
    raise UnimplementedError("unsupported grad_clip %r in dygraph"
                             % (grad_clip,))


def _eager_regularize(reg, pairs):
    from .. import regularizer as R
    if reg is None:
        return pairs
    if isinstance(reg, R.L2DecayRegularizer):
        return [(p, g + reg._coeff * p.value) for p, g in pairs]
    if isinstance(reg, R.L1DecayRegularizer):
        return [(p, g + reg._coeff * jnp.sign(p.value))
                for p, g in pairs]
    raise UnimplementedError("unsupported regularizer %r in dygraph"
                             % (reg,))


def apply_dygraph(opt, loss: VarBase, parameter_list=None,
                  grad_clip=None):
    """minimize() in dygraph mode: backward + eager per-param update
    (with the same clip -> regularize -> update order as the static
    path). Returns the [(param, grad)] list like the static minimize."""
    loss.backward()
    params = [p for p in (parameter_list or [])] or _collect_params(loss)
    name = type(opt).__name__.lower()
    pairs = [(p, p.grad) for p in params
             if p.grad is not None and getattr(p, "trainable", True)]
    if grad_clip is not None:
        pairs = _eager_clip(grad_clip, pairs)
    pairs = _eager_regularize(opt.regularization, pairs)
    result = []
    # ONE schedule tick per minimize, not per parameter: a callable
    # learning rate (dygraph.LearningRateDecay) advances its step
    # counter on every call
    lr = _lr(opt)
    for p, g in pairs:
        st = _state(opt).setdefault(id(p), {})
        if name.startswith("sgd"):
            p.value = ops.get("sgd").fn(p.value, g, lr)
        elif name.startswith("momentum"):
            v = st.setdefault("velocity", jnp.zeros_like(p.value))
            p.value, st["velocity"] = ops.get("momentum").fn(
                p.value, g, v, lr, mu=opt._momentum,
                use_nesterov=opt._use_nesterov)
        elif name.startswith("adamax"):
            mom = st.setdefault("moment", jnp.zeros_like(p.value))
            inf = st.setdefault("inf_norm", jnp.zeros_like(p.value))
            b1p = st.setdefault("b1p", jnp.float32(opt._beta1))
            (p.value, st["moment"], st["inf_norm"],
             st["b1p"]) = ops.get("adamax").fn(
                p.value, g, mom, inf, b1p, lr, beta1=opt._beta1,
                beta2=opt._beta2, epsilon=opt._epsilon)
        elif name.startswith("adamw") or name.startswith("adam"):
            m1 = st.setdefault("m1", jnp.zeros_like(p.value))
            m2 = st.setdefault("m2", jnp.zeros_like(p.value))
            b1p = st.setdefault("b1p", jnp.float32(opt._beta1))
            b2p = st.setdefault("b2p", jnp.float32(opt._beta2))
            kw = dict(beta1=opt._beta1, beta2=opt._beta2,
                      epsilon=opt._epsilon)
            if name.startswith("adamw"):
                kw["weight_decay"] = getattr(opt, "_weight_decay",
                                             0.01)
            (p.value, st["m1"], st["m2"], st["b1p"],
             st["b2p"]) = ops.get(
                "adamw" if name.startswith("adamw") else "adam").fn(
                p.value, g, m1, m2, b1p, b2p, lr, **kw)
        elif name.startswith("adagrad"):
            mom = st.setdefault("moment", jnp.zeros_like(p.value))
            p.value, st["moment"] = ops.get("adagrad").fn(
                p.value, g, mom, lr, epsilon=opt._epsilon)
        else:
            raise UnimplementedError(
                "optimizer %s has no dygraph (eager) path yet; use "
                "SGD/Momentum/Adam/AdamW/Adagrad or the static-graph "
                "mode" % type(opt).__name__)
        result.append((p, g))
        p.grad = None
    return result


def _collect_params(loss):
    """Without an explicit parameter_list, dygraph users pass one via
    optimizer ctor in 2.x; in 1.x minimize finds params from the
    autograd graph. The tape is cleared by backward(), so require the
    caller's list instead."""
    raise UnimplementedError(
        "dygraph minimize() needs parameter_list=layer.parameters()")
