"""Dygraph checkpointing (reference:
python/paddle/fluid/dygraph/checkpoint.py save_dygraph/load_dygraph —
state dicts to disk)."""

from __future__ import annotations

import os

import numpy as np

from ..core.enforce import NotFoundError, enforce


def save_dygraph(state_dict, model_path):
    """Save a ``Layer.state_dict()`` (or optimizer state) to
    ``model_path + '.pdparams'`` as an npz archive (replaces the
    reference's LoDTensor stream serialization)."""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    np.savez(model_path + ".pdparams", **arrays)


def load_dygraph(model_path):
    """Returns (param_state_dict, optimizer_state_dict|None)."""
    path = model_path + ".pdparams"
    if not os.path.exists(path):
        path = model_path + ".pdparams.npz"
    enforce(os.path.exists(path),
            "no dygraph checkpoint at %r" % model_path)
    with np.load(path) as z:
        state = {k: z[k] for k in z.files}
    return state, None
