"""BackwardStrategy config (reference:
python/paddle/fluid/dygraph/backward_strategy.py ->
imperative/backward_strategy.h: one knob, ``sort_sum_gradient`` —
deterministic gradient aggregation order).

TPU note: the eager tape already aggregates gradients
deterministically (a Python list walked in reverse-creation order),
so the flag is accepted for parity and recorded; both settings
produce identical sums here."""

from __future__ import annotations

__all__ = ["BackwardStrategy"]


class BackwardStrategy:
    def __init__(self):
        self.sort_sum_gradient = False
