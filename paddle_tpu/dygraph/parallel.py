"""Dygraph data parallelism (reference:
python/paddle/fluid/dygraph/parallel.py:84 DataParallel —
scale_loss:150 + apply_collective_grads:171 over NCCL,
imperative/nccl_context.cc).

TPU-native redesign: eager JAX arrays carry shardings — placing the
batch on a dp mesh makes every eager op (and the tape backward) run
SPMD with compiler-inserted ICI collectives. Gradients arrive already
summed across shards, so scale_loss/apply_collective_grads are kept
for API parity but the collectives they hand-coded are implicit."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.mesh import data_parallel_mesh
from .base import VarBase
from .layers import Layer


class ParallelEnv:
    """Reference: dygraph/parallel.py Env (trainer env vars). Single-
    process SPMD: rank 0 of 1 host, n local devices."""

    def __init__(self):
        self.nranks = jax.device_count()
        self.local_rank = 0
        self.dev_id = 0
        self.trainer_endpoints = []
        self.current_endpoint = ""


prepare_context = ParallelEnv  # 1.x API alias


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers
        self._mesh = data_parallel_mesh()

    def forward(self, *inputs, **kwargs):
        sharded = []
        for x in inputs:
            if isinstance(x, VarBase) and x.value.ndim > 0 and \
                    x.value.shape[0] % self._mesh.devices.size == 0:
                spec = PartitionSpec(
                    "dp", *([None] * (x.value.ndim - 1)))
                x = VarBase(jax.device_put(
                    x.value, NamedSharding(self._mesh, spec)),
                    stop_gradient=x.stop_gradient, name=x.name)
            sharded.append(x)
        return self._layers(*sharded, **kwargs)

    def scale_loss(self, loss):
        """Grad averaging is part of the SPMD mean-loss math; identity
        kept for parity with the reference's 1/nranks scaling."""
        return loss

    def apply_collective_grads(self):
        """No-op: gradients of replicated params under SPMD eager are
        already globally reduced by XLA."""
        return

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
