"""Dygraph (eager) mode core: guard / to_variable / tape autograd.

Reference: paddle/fluid/imperative/ (Tracer::Trace tracer.cc:140,
VarBase layer.h:116, autograd engine engine.h:25) and
python/paddle/fluid/dygraph/base.py (guard, to_variable).

TPU-native redesign: eager ops execute the SAME pure-JAX lowerings the
static Executor traces (one registry, ops/), on concrete device
arrays. Autograd is a Python tape: each executed op records (opdef,
attrs, inputs, outputs); ``VarBase.backward()`` walks the tape in
reverse pulling cotangents through ``jax.vjp`` of each lowering — the
eager twin of executor._run_vjp_op, replacing the reference's
per-op-registered grad chains (imperative/layer.cc). jit still applies
inside whole ops; for full-step fusion users switch to the static
Program path (same layer vocabulary)."""

from __future__ import annotations

import contextlib
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops
from ..core.enforce import InvalidArgumentError, enforce
from ..core.flags import FLAGS
from ..framework import convert_dtype

_in_dygraph = False
_tape: List["_TapeEntry"] = []
_no_grad_depth = 0
_rng_counter = 0


def enabled() -> bool:
    return _in_dygraph


in_dygraph_mode = enabled


@contextlib.contextmanager
def guard(place=None):
    """Reference: dygraph/base.py guard()."""
    global _in_dygraph, _tape, _rng_counter
    prev = _in_dygraph
    _in_dygraph = True
    try:
        yield
    finally:
        _in_dygraph = prev
        if not prev:
            _tape = []
            _rng_counter = 0


@contextlib.contextmanager
def no_grad():
    global _no_grad_depth
    _no_grad_depth += 1
    try:
        yield
    finally:
        _no_grad_depth -= 1


class VarBase:
    """Eager tensor (reference: imperative/layer.h:116 VarBase =
    value + grad + stop_gradient)."""

    def __init__(self, value, stop_gradient=True, name=None):
        self.value = value if isinstance(value, jax.Array) \
            else jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self.name = name
        self.grad: Optional[jax.Array] = None

    # -- fluid VarBase API --------------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def numpy(self):
        return np.asarray(self.value)

    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        return VarBase(self.value, stop_gradient=True, name=self.name)

    def astype(self, dtype):
        return run_dygraph_op("cast", {"X": [self]},
                              {"dtype": convert_dtype(dtype)})

    def backward(self, retain_graph=False):
        backward(self, retain_graph=retain_graph)

    # -- operator sugar (math_op_patch analog) ------------------------------
    def _binary(self, other, op_type, reverse=False):
        if not isinstance(other, VarBase):
            other = VarBase(jnp.asarray(other, self.value.dtype))
        x, y = (other, self) if reverse else (self, other)
        return run_dygraph_op(op_type, {"X": [x], "Y": [y]},
                              {"axis": -1})

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __neg__(self):
        return run_dygraph_op("scale", {"X": [self]}, {"scale": -1.0})

    def __repr__(self):
        return "VarBase(%s, shape=%s, dtype=%s)" % (
            self.name or "", self.shape, self.dtype)


def to_variable(value, name=None, zero_copy=None):
    """Reference: dygraph/base.py to_variable."""
    enforce(_in_dygraph,
            "to_variable must be called under dygraph.guard()")
    if isinstance(value, VarBase):
        return value
    return VarBase(jnp.asarray(value), stop_gradient=True, name=name)


class _TapeEntry:
    """One recorded op. Outputs are held by WEAKREF: an inference
    output the user drops dies, and the sweep in run_dygraph_op then
    reclaims the entry (and the device arrays its inputs pin) — the
    eager analog of the reference freeing per-tensor autograd graphs
    when tensors die (ADVICE r1: long no-backward loops used to grow
    the tape unboundedly). Live chains are safe: any output consumed
    by a later op is strongly referenced by that op's slot_vals."""

    __slots__ = ("opdef", "attrs", "slot_vals", "out_refs")

    def __init__(self, opdef, attrs, slot_vals, out_vbs):
        self.opdef = opdef
        self.attrs = attrs
        self.slot_vals = slot_vals  # list aligned with input_slots
        self.out_refs = [weakref.ref(vb) for vb in out_vbs]

    def outs(self):
        return [r() for r in self.out_refs]

    def dead(self):
        return all(r() is None for r in self.out_refs)


def _sweep_tape():
    """Drop entries whose every output died — nothing can request
    gradients through them. One REVERSE pass reclaims whole dead
    chains in O(n): slots are nulled (releasing the entry object and
    hence its strong input refs) the moment an entry is found dead,
    so by the time the scan reaches the predecessor its outputs have
    already died too."""
    changed = False
    for i in range(len(_tape) - 1, -1, -1):
        e = _tape[i]
        if e is not None and e.dead():
            _tape[i] = None
            changed = True
        e = None  # drop the local ref so the entry frees NOW
    if changed:
        _tape[:] = [e for e in _tape if e is not None]


def _next_rng():
    global _rng_counter
    _rng_counter += 1
    seed = FLAGS.global_seed or 0
    return jax.random.fold_in(jax.random.key(seed), _rng_counter)


def run_dygraph_op(op_type, inputs: Dict[str, List[VarBase]],
                   attrs: Dict[str, Any]):
    """Execute one op eagerly through its registered lowering and
    record it on the tape (reference: Tracer::Trace,
    imperative/tracer.cc:140)."""
    opdef = ops.get(op_type)
    attrs = {k: v for k, v in attrs.items()
             if k not in ("op_role", "op_namescope")}
    if opdef.needs_rng:
        attrs["rng"] = _next_rng()

    slot_vals = []
    for slot, variadic in opdef.input_slots:
        vbs = inputs.get(slot, [])
        if variadic:
            slot_vals.append(list(vbs))
        elif not vbs:
            slot_vals.append(None)
        else:
            slot_vals.append(vbs[0])

    def raw(v):
        if v is None:
            return None
        if isinstance(v, list):
            return [x.value for x in v]
        return v.value

    lib = FLAGS.op_library or None
    fn = opdef.pick(lib)
    result = fn(*[raw(v) for v in slot_vals], **attrs)

    # record only when some differentiable input is grad-connected —
    # outputs of unrecorded ops become stop_gradient barriers, pruning
    # backward work (reference: VarBase stop_gradient propagation)
    record = _no_grad_depth == 0 and opdef.differentiable
    if record:
        record = False
        for i, (slot, _variadic) in enumerate(opdef.input_slots):
            if slot in opdef.nondiff_slots:
                continue
            v = slot_vals[i]
            vbs = v if isinstance(v, list) else ([v] if v else [])
            for vb in vbs:
                if _is_float(vb.value) and (
                        not vb.stop_gradient or
                        getattr(vb, "is_parameter", False)):
                    record = True
                    break
            if record:
                break

    nslots = len(opdef.output_slots)
    if nslots == 1:
        result = (result,)
    out_vbs = []
    outs = []
    for slot, val in zip(opdef.output_slots, result):
        variadic = slot.endswith("*")
        if variadic:
            vb_list = [VarBase(v, stop_gradient=not record)
                       for v in val]
            out_vbs.extend(vb_list)
            outs.append(vb_list)
        else:
            vb = VarBase(val, stop_gradient=not record)
            out_vbs.append(vb)
            outs.append(vb)

    if record:
        _tape.append(_TapeEntry(opdef, attrs, slot_vals, out_vbs))
        if len(_tape) % 256 == 0:
            _sweep_tape()

    if len(outs) == 1:
        return outs[0]
    return tuple(outs)


def _is_float(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def backward(loss: VarBase, retain_graph=False):
    """Tape-walk reverse AD (reference: imperative engine.h:25; the
    eager twin of executor._run_vjp_op)."""
    enforce(_in_dygraph, "backward() requires dygraph mode")
    grads: Dict[int, jax.Array] = {
        id(loss): jnp.ones_like(loss.value)}
    touched: Dict[int, VarBase] = {}

    for entry in reversed(_tape):
        entry_outs = entry.outs()
        if not any(vb is not None and id(vb) in grads
                   for vb in entry_outs):
            continue
        opdef, attrs = entry.opdef, entry.attrs

        diff = []  # (pos-in-slot_vals, variadic, VarBase or list)
        for i, (slot, variadic) in enumerate(opdef.input_slots):
            v = entry.slot_vals[i]
            if v is None or slot in opdef.nondiff_slots:
                continue
            if variadic:
                if v and all(_is_float(x.value) for x in v):
                    diff.append((i, True, v))
            elif _is_float(v.value):
                diff.append((i, False, v))
        if not diff:
            continue

        def fwd(*dvals):
            vals = []
            for i, (slot, variadic) in enumerate(opdef.input_slots):
                v = entry.slot_vals[i]
                if v is None:
                    vals.append(None)
                elif isinstance(v, list):
                    vals.append([x.value for x in v])
                else:
                    vals.append(v.value)
            for (i, variadic, _vb), dv in zip(diff, dvals):
                vals[i] = dv
            return opdef.fn(*vals, **attrs)

        primals = []
        for i, variadic, vb in diff:
            primals.append([x.value for x in vb] if variadic
                           else vb.value)
        outs, pull = jax.vjp(fwd, *primals)
        flat_out, tree = jax.tree_util.tree_flatten(outs)
        cots = []
        for val, vb in zip(flat_out, entry_outs):
            g = grads.get(id(vb)) if vb is not None else None
            cots.append(g if g is not None else jnp.zeros_like(val))
        cots += [jnp.zeros_like(v)
                 for v in flat_out[len(entry.out_refs):]]
        in_grads = pull(jax.tree_util.tree_unflatten(tree, cots))

        for (i, variadic, vb), g in zip(diff, in_grads):
            targets = vb if variadic else [vb]
            gs = g if variadic else [g]
            for t, gi in zip(targets, gs):
                # stop_gradient barriers (non-parameter) end the chain
                if t.stop_gradient and \
                        not getattr(t, "is_parameter", False):
                    continue
                key = id(t)
                grads[key] = grads[key] + gi if key in grads else gi
                touched[key] = t

    # expose accumulated grads (repeated backward() calls accumulate,
    # as in the reference; clear_gradient()/optimizer clears them)
    for key, vb in touched.items():
        vb.grad = grads[key] if vb.grad is None else \
            (vb.grad + grads[key])

    if not retain_graph:
        _tape.clear()
